"""MiniMax M3 (+VL): block-sparse DSA on the het engine, gemma norms,
swigluoai, CLIP 3D-rope tower + projector/patch-merger.

Reference: nemo_automodel/components/models/minimax_m3_vl/ (layers.py
select_sparse_blocks, vision_encoder.py, state_dict_adapter.py).
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from automodel_tpu.models.moe_lm import het_moe
from automodel_tpu.models.registry import get_model_spec
from automodel_tpu.models.vlm import minimax_m3_vl

M3_TEXT_HF = {
    "architectures": ["MiniMaxM3SparseForCausalLM"],
    "model_type": "minimax_m3",
    "vocab_size": 128,
    "hidden_size": 32,
    "intermediate_size": 16,          # moe expert width
    "dense_intermediate_size": 64,
    "shared_intermediate_size": 16,
    "num_hidden_layers": 3,
    "num_attention_heads": 4,
    "num_key_value_heads": 2,
    "head_dim": 8,
    "rotary_dim": 4,                  # partial rope
    "rope_theta": 5000000.0,
    "use_gemma_norm": True,
    "use_qk_norm": True,
    "num_local_experts": 4,
    "num_experts_per_tok": 2,
    "n_shared_experts": 1,
    "scoring_func": "sigmoid",
    "use_routing_bias": True,
    "routed_scaling_factor": 2.0,
    "moe_layer_freq": [0, 1, 1],      # layer 0 dense
    "sparse_attention_config": {
        "use_sparse_attention": True,
        "sparse_attention_freq": [0, 1, 1],   # layers 1-2 sparse
        "sparse_num_index_heads": 2,
        "sparse_index_dim": 8,
        "sparse_block_size": 4,
        # 3 = 1 forced init + 1 forced local + ONE score-driven free block,
        # so the indexer genuinely selects (a budget of 2 would be fully
        # consumed by the forced blocks and scores would never matter)
        "sparse_topk_blocks": 3,
        "sparse_init_block": 1,
        "sparse_local_block": 1,
        "sparse_score_type": "max",
    },
    "rms_norm_eps": 1e-6,
}

M3_VL_HF = {
    "architectures": ["MiniMaxM3SparseForConditionalGeneration"],
    "model_type": "minimax_m3_vl",
    "image_token_index": 120,
    "projector_hidden_size": 48,
    "multimodal_projector_bias": True,
    "patch_merge_bias": True,
    "vision_config": {
        "hidden_size": 32, "num_attention_heads": 2, "num_hidden_layers": 2,
        "intermediate_size": 48, "patch_size": 14,
        "img_token_compression_config": {
            "spatial_merge_size": 2, "temporal_patch_size": 2,
        },
    },
    "text_config": dict(M3_TEXT_HF, architectures=["MiniMaxM3SparseForCausalLM"]),
}


def _text_setup():
    spec = get_model_spec(M3_TEXT_HF)
    cfg = spec.config_from_hf(M3_TEXT_HF, dtype=jnp.float32, remat_policy="none")
    return spec, cfg, het_moe.init(cfg, jax.random.key(0))


def test_m3_config_mapping():
    spec, cfg, params = _text_setup()
    assert cfg.mlp_kinds == ("dense", "moe", "moe")
    assert cfg.sparse_attn == (False, True, True)
    assert cfg.zero_centered_norm and cfg.dense_activation == "swigluoai"
    assert cfg.moe.score_func == "sigmoid" and cfg.moe.route_scale == 2.0
    assert cfg.moe.expert_activation == "swigluoai"
    assert cfg.share_expert_dim == 16
    assert cfg.partial_rotary == (0.5,) * 3
    assert "indexer" in params
    assert params["indexer"]["index_q_proj"]["kernel"].shape == (2, 32, 16)
    # gemma norms init zero-centered
    assert float(jnp.abs(params["final_norm"]["scale"]).max()) == 0.0


@pytest.mark.slow
def test_m3_accepts_linear_precision_override():
    """The recipe forwards model.linear_precision to every config builder;
    the het engine must accept it (int8 path smoke)."""
    spec = get_model_spec(M3_TEXT_HF)
    cfg = spec.config_from_hf(
        M3_TEXT_HF, dtype=jnp.float32, remat_policy="none", linear_precision="int8"
    )
    assert cfg.linear_precision == "int8"
    params = het_moe.init(cfg, jax.random.key(0))
    ids = jnp.asarray(np.random.default_rng(0).integers(1, 128, (1, 8)), jnp.int32)
    logits, _ = het_moe.forward(params, cfg, ids)
    assert np.isfinite(np.asarray(logits)).all()


@pytest.mark.slow
def test_m3_forward_finite_and_sparse_is_live():
    spec, cfg, params = _text_setup()
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(1, 128, (2, 24), dtype=np.int32))
    logits, aux, stats = het_moe.forward(params, cfg, ids, return_stats=True)
    assert logits.shape == (2, 24, 128)
    assert np.isfinite(np.asarray(logits)).all()
    assert stats["tokens_per_expert"].shape == (2, 4)

    # the indexer is live: perturbing index_q_proj changes the selection →
    # changes the logits (block_size=4, topk=2, S=24 → 6 blocks, real topk)
    p2 = jax.tree.map(lambda x: x, params)
    p2["indexer"] = jax.tree.map(lambda x: x, params["indexer"])
    p2["indexer"]["index_q_proj"] = {
        "kernel": params["indexer"]["index_q_proj"]["kernel"][::-1]
    }
    l2, _ = het_moe.forward(p2, cfg, ids)
    assert np.abs(np.asarray(logits) - np.asarray(l2)).max() > 1e-6


def test_select_sparse_blocks_semantics():
    """Pinned to the reference selection rules (layers.py:124): causal
    block visibility, forced init/local blocks, top-k of the rest."""
    B, S, Hi, Di = 1, 12, 1, 8
    rng = np.random.default_rng(3)
    idx_q = jnp.asarray(rng.normal(size=(B, S, Hi, Di)).astype(np.float32))
    idx_k = jnp.asarray(rng.normal(size=(B, S, Di)).astype(np.float32))
    positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32)[None], (B, S))
    keep = np.asarray(het_moe.select_sparse_blocks(
        idx_q, idx_k, positions,
        block_size=4, topk_blocks=2, init_blocks=1, local_blocks=1,
    ))
    assert keep.dtype == np.bool_
    assert keep.shape == (1, 1, 12, 12)
    # token-level causal always holds
    assert not np.triu(keep[0, 0], 1).any()
    # init block (keys 0-3) visible to every query at its causal prefix
    for qi in range(12):
        lim = qi + 1
        assert keep[0, 0, qi, : min(4, lim)].all()
    # current (local) block always kept: the diagonal is attendable
    assert all(keep[0, 0, qi, qi] for qi in range(12))
    # budget: 2 blocks max → a query in block 2 sees ≤ 2*4 causal keys
    q = 11
    assert keep[0, 0, q].sum() <= 2 * 4


@pytest.mark.slow
def test_m3_sparse_equals_dense_when_budget_covers_all():
    """topk_blocks ≥ num_blocks ⇒ every causal block selected ⇒ sparse
    attention equals dense attention exactly."""
    hf = json.loads(json.dumps(M3_TEXT_HF))
    hf["sparse_attention_config"]["sparse_topk_blocks"] = 64
    spec = get_model_spec(M3_TEXT_HF)
    cfg_sp = spec.config_from_hf(hf, dtype=jnp.float32, remat_policy="none")
    hf_dense = json.loads(json.dumps(hf))
    hf_dense["sparse_attention_config"]["use_sparse_attention"] = False
    cfg_d = spec.config_from_hf(hf_dense, dtype=jnp.float32, remat_policy="none")
    params = het_moe.init(cfg_sp, jax.random.key(1))
    dense_params = {k: v for k, v in params.items() if k != "indexer"}
    rng = np.random.default_rng(1)
    ids = jnp.asarray(rng.integers(1, 128, (2, 16), dtype=np.int32))
    l_sp, _ = het_moe.forward(params, cfg_sp, ids)
    l_d, _ = het_moe.forward(dense_params, cfg_d, ids)
    np.testing.assert_allclose(np.asarray(l_sp), np.asarray(l_d), atol=2e-5)


@pytest.mark.slow
def test_m3_packed_documents_match_separate_forwards():
    """Packed batch (document-local positions + segment_ids) with a FULL
    selection budget: every token's logits must equal the unpacked
    per-document forward — sparse block selection runs over key ROWS with a
    segment AND, so no cross-document leakage and no wrong-row causality
    (reference eager path: row-causal tril ∧ padding mask). Under a
    CONSTRAINED budget exact per-doc parity does not hold (selection can
    spend blocks on other documents, matching the reference's
    post-selection AND — layers.py:490) so the full budget isolates the
    geometry."""
    import dataclasses

    spec, cfg, params = _text_setup()
    cfg = dataclasses.replace(cfg, sparse_topk_blocks=64)
    rng = np.random.default_rng(5)
    d1 = rng.integers(1, 128, (1, 10), dtype=np.int32)
    d2 = rng.integers(1, 128, (1, 14), dtype=np.int32)
    packed = jnp.asarray(np.concatenate([d1, d2], axis=1))
    seg = jnp.asarray([[0] * 10 + [1] * 14])
    pos = jnp.asarray([list(range(10)) + list(range(14))], jnp.int32)
    lp, _ = het_moe.forward(params, cfg, packed, positions=pos, segment_ids=seg)
    l1, _ = het_moe.forward(params, cfg, jnp.asarray(d1))
    l2, _ = het_moe.forward(params, cfg, jnp.asarray(d2))
    np.testing.assert_allclose(np.asarray(lp[0, :10]), np.asarray(l1[0]), atol=2e-5)
    np.testing.assert_allclose(np.asarray(lp[0, 10:]), np.asarray(l2[0]), atol=2e-5)


@pytest.mark.slow
def test_m3_text_adapter_roundtrip():
    from automodel_tpu.checkpoint.hf_adapter import get_adapter

    spec, cfg, params = _text_setup()
    ad = get_adapter(spec.adapter_name, cfg, **spec.adapter_kwargs)
    sd = dict(ad.to_hf(params))
    assert "model.layers.1.self_attn.index_q_proj.weight" in sd
    assert "model.layers.0.self_attn.index_q_proj.weight" not in sd
    assert sd["model.layers.1.block_sparse_moe.experts.0.w1.weight"].shape == (16, 32)
    assert "model.layers.1.block_sparse_moe.e_score_correction_bias" in sd
    assert "model.layers.1.block_sparse_moe.shared_experts.up_proj.weight" in sd
    assert "model.layers.0.mlp.gate_proj.weight" in sd  # dense layer
    p2 = ad.from_hf(lambda k: np.asarray(sd[k]))
    rng = np.random.default_rng(2)
    ids = jnp.asarray(rng.integers(1, 128, (1, 12), dtype=np.int32))
    o1, _ = het_moe.forward(params, cfg, ids)
    o2, _ = het_moe.forward(jax.tree.map(jnp.asarray, p2), cfg, ids)
    np.testing.assert_allclose(np.asarray(o1), np.asarray(o2), atol=1e-5)


def _vl_setup():
    spec = get_model_spec(M3_VL_HF)
    cfg = spec.config_from_hf(M3_VL_HF, dtype=jnp.float32, remat_policy="none")
    return spec, cfg, minimax_m3_vl.init(cfg, jax.random.key(0))


def _vl_batch(cfg, B=2, S=24, img=56):
    m = cfg.vision.spatial_merge_size
    n_img = (img // cfg.vision.patch_size // m) ** 2
    rng = np.random.default_rng(0)
    text = rng.integers(1, 100, (B, S - n_img), dtype=np.int32)
    ids = np.concatenate(
        [text[:, :4], np.full((B, n_img), cfg.image_token_id, np.int32), text[:, 4:]],
        axis=1,
    )
    pixels = rng.normal(size=(B, img, img, 3)).astype(np.float32)
    return jnp.asarray(ids), jnp.asarray(pixels)


@pytest.mark.slow
def test_m3_vl_forward_image_conditioned():
    spec, cfg, params = _vl_setup()
    ids, pixels = _vl_batch(cfg)
    logits, aux, stats = minimax_m3_vl.forward(
        params, cfg, ids, pixels, return_stats=True
    )
    assert logits.shape == (2, 24, 128)
    assert np.isfinite(np.asarray(logits)).all()
    l2, _ = minimax_m3_vl.forward(params, cfg, ids, pixels + 1.0)
    assert np.abs(np.asarray(logits) - np.asarray(l2)).max() > 1e-5


@pytest.mark.slow
def test_m3_vl_generate_runs():
    from automodel_tpu.inference.generate import GenerateConfig, vlm_generate

    spec, cfg, params = _vl_setup()
    ids, pixels = _vl_batch(cfg, B=1)
    out = vlm_generate(
        minimax_m3_vl, params, cfg, ids, pixels,
        jax.random.key(1), GenerateConfig(max_new_tokens=4),
    )
    assert out.shape == (1, 28)


@pytest.mark.slow
def test_m3_vl_adapter_roundtrip():
    from automodel_tpu.checkpoint.hf_adapter import get_adapter

    spec, cfg, params = _vl_setup()
    ad = get_adapter(spec.adapter_name, cfg, **spec.adapter_kwargs)
    sd = dict(ad.to_hf(params))
    assert sd[
        "vision_tower.vision_model.embeddings.patch_embedding.weight"
    ].shape == (32, 3, 2, 14, 14)
    assert "vision_tower.vision_model.pre_layrnorm.weight" in sd
    assert "multi_modal_projector.linear_1.weight" in sd
    assert "patch_merge_mlp.linear_2.bias" in sd
    assert "language_model.lm_head.weight" in sd
    assert "language_model.model.layers.1.block_sparse_moe.experts.0.w2.weight" in sd
    p2 = ad.from_hf(lambda k: np.asarray(sd[k]))
    ids, pixels = _vl_batch(cfg, B=1)
    o1, _ = minimax_m3_vl.forward(params, cfg, ids, pixels)
    o2, _ = minimax_m3_vl.forward(jax.tree.map(jnp.asarray, p2), cfg, ids, pixels)
    np.testing.assert_allclose(np.asarray(o1), np.asarray(o2), atol=1e-5)


@pytest.mark.recipe
@pytest.mark.slow  # compile-heavy VL recipe; m3_vl numerics pinned in test_model_pins
def test_m3_vl_recipe_trains(tmp_path):
    from automodel_tpu.cli.app import resolve_recipe_class
    from automodel_tpu.config import ConfigNode

    cfg = ConfigNode({
        "seed": 7,
        "run_dir": str(tmp_path),
        "auto_resume": False,
        "recipe": "vlm_finetune",
        "model": {"hf_config": M3_VL_HF, "dtype": "float32", "remat_policy": "none"},
        "distributed": {"dp_shard": -1},
        "dataset": {
            "_target_": "automodel_tpu.datasets.vlm.MockVLMDatasetConfig",
            "num_samples": 16, "seq_len": 24, "vocab_size": 128,
            "image_size": 56, "patch_size": 14, "merge_factor": 2,
            "image_token_id": 120,
        },
        "dataloader": {"microbatch_size": 8, "grad_acc_steps": 1},
        "optimizer": {"name": "adamw", "lr": 1e-3},
        "lr_scheduler": {"style": "constant", "warmup_steps": 0},
        "step_scheduler": {"max_steps": 2, "ckpt_every_steps": 100},
        "checkpoint": {"enabled": False},
        "loss": {"chunk_size": 64},
    })
    r = resolve_recipe_class(cfg)(cfg)
    r.setup()
    r.run_train_validation_loop()
    recs = [json.loads(l) for l in open(tmp_path / "training.jsonl") if l.strip()]
    assert len(recs) == 2
    assert all(np.isfinite(x["loss"]) for x in recs)
