"""Registry-completeness guard: every model family the registry exposes
must be backed by verification — a committed golden pin
(tests/golden_values/model_pins/, exercised by test_model_pins.py) or an
HF-parity/golden test — or be EXPLICITLY allowlisted as a known gap.

The allowlist is the contract: it may only SHRINK. Adding a family to the
registry without a pin or parity test fails here (extend coverage or
consciously allowlist it in review); landing coverage for an allowlisted
family also fails until the entry is removed (the list can't silently
absorb stale entries)."""

import pathlib

import pytest

from automodel_tpu.models.registry import MODEL_ARCH_MAPPING

TESTS_DIR = pathlib.Path(__file__).parent
PIN_DIR = TESTS_DIR.parent / "golden_values" / "model_pins"

#: family -> (test file, test name) of the HF-parity/golden-logit test that
#: verifies it. Pointers are checked against the file's source so a renamed
#: or deleted test fails here instead of silently dropping coverage.
PARITY_TESTS = {
    "llama": ("test_hf_parity.py", "test_llama_logits_match_hf"),
    "qwen2": ("test_hf_parity.py", "test_qwen2_logits_match_hf"),
    "mixtral": ("test_hf_parity.py", "test_mixtral_logits_match_hf"),
    "qwen3_next": ("test_hf_parity.py", "test_qwen3_next_logits_match_hf"),
    "glm4": ("test_hf_parity.py", "test_glm4_logits_match_hf"),
    "glm4_moe": ("test_hf_parity.py", "test_glm4_moe_logits_match_hf"),
    "ernie4_5": ("test_hf_parity.py", "test_ernie4_5_logits_match_hf"),
    "ernie4_5_moe": ("test_hf_parity.py", "test_ernie4_5_moe_logits_match_hf"),
    "gemma3": ("test_hf_parity.py", "test_gemma3_logits_match_hf"),
    "hunyuan_dense": ("test_hf_parity.py", "test_hunyuan_dense_logits_match_hf"),
    "hunyuan_moe": ("test_hf_parity.py", "test_hunyuan_moe_logits_match_hf"),
    "minimax_m2": ("test_hf_parity.py", "test_minimax_m2_adapter_roundtrip"),
    "llama_bidirectional": (
        "test_hf_parity.py", "test_llama_bidirectional_loads_and_attends_both_ways"
    ),
    "mamba2": ("test_hf_parity.py", "test_mamba2_logits_match_hf"),
}

#: Known gaps — families with functional tests (adapter roundtrips, recipe
#: smoke, component parity) but NO pinned logits and NO torch/HF-oracle
#: parity test yet. Remove an entry when its pin or parity test lands; do
#: not add entries outside review.
ALLOWLIST_KNOWN_GAPS = {
    "deepseek_v3",    # exercised via test_moe.py registry/forward only
    "deepseek_v32",   # DSA variant of v3; component parity in test_dsa.py
    "deepseek_v4",    # test_dsa.py recipe smoke; no pinned logits
    "gemma2",         # test_decoder/test_generate functional only
    "glm4_moe_lite",  # test_model_tail roundtrip only
    "gpt_oss",        # test_moe.py (swigluoai/bias experts) only
    "hy_mt2",         # test_model_tail roundtrip only
    "kimi_k2",        # covered indirectly via kimi_vl text backbone
    "kimi_k25_vl",    # test_kimi_vl variant test; no pin
    "llava",          # test_vlm hf-roundtrip (weights), no logits oracle
    "llava_onevision",  # shares the llava module; no dedicated test
    "ministral3",     # test_model_tail forward only
    "ministral_bidirectional",  # test_model_tail bidirectional check only
    "mistral",        # adapter shared with llama; no dedicated parity
    "mistral4",       # test_model_tail QPE scaling only
    "nemotron_h",     # test_nemotron_h structural/causality tests
    "omni",           # test_omni forward/roundtrip only
    "qwen3",          # test_model_pins uses it as a backbone, no own pin
    "qwen3_moe",      # structural tests via test_moe only
}


def _registry_families() -> set:
    return {spec.name for spec in MODEL_ARCH_MAPPING.values()}


def _pinned_families() -> set:
    return {p.stem for p in PIN_DIR.glob("*.json")}


def test_every_family_verified_or_allowlisted():
    families = _registry_families()
    covered = _pinned_families() | set(PARITY_TESTS)
    missing = families - covered - ALLOWLIST_KNOWN_GAPS
    assert not missing, (
        f"registry families with no golden pin, no HF-parity test, and no "
        f"allowlist entry: {sorted(missing)} — add a pin "
        "(AM_WRITE_PINS=1 pytest tests/unit/test_model_pins.py) or a parity "
        "test, or (review-gated) extend ALLOWLIST_KNOWN_GAPS"
    )


def test_allowlist_only_shrinks():
    """An allowlisted family that GAINS coverage must leave the list, and
    entries must name real registry families (no zombie entries)."""
    families = _registry_families()
    covered = _pinned_families() | set(PARITY_TESTS)
    stale = ALLOWLIST_KNOWN_GAPS & covered
    assert not stale, (
        f"allowlisted families now have coverage: {sorted(stale)} — remove "
        "them from ALLOWLIST_KNOWN_GAPS (the list only shrinks)"
    )
    zombie = ALLOWLIST_KNOWN_GAPS - families
    assert not zombie, f"allowlist names unknown families: {sorted(zombie)}"


def test_parity_pointers_resolve():
    for fam, (fname, tname) in PARITY_TESTS.items():
        path = TESTS_DIR / fname
        assert path.exists(), f"{fam}: {fname} missing"
        assert f"def {tname}(" in path.read_text(), (
            f"{fam}: {fname} no longer defines {tname} — update PARITY_TESTS"
        )


def test_pins_on_disk_are_exercised():
    """Every committed pin file corresponds to a FAMILIES entry in
    test_model_pins.py (orphan pins = dead weight that looks like
    coverage), and vice versa every FAMILIES entry has its pin committed."""
    import ast

    src = (TESTS_DIR / "test_model_pins.py").read_text()
    for node in ast.walk(ast.parse(src)):
        if (
            isinstance(node, ast.Assign)
            and getattr(node.targets[0], "id", "") == "FAMILIES"
        ):
            exercised = {k.value for k in node.value.keys}
            break
    else:  # pragma: no cover
        pytest.fail("FAMILIES dict not found in test_model_pins.py")
    pins = _pinned_families()
    assert pins == exercised, (
        f"orphan pins: {sorted(pins - exercised)}; "
        f"missing pins: {sorted(exercised - pins)}"
    )
