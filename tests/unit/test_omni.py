"""Omni (text·image·audio) tier: audio encoder, omni merge, adapter
roundtrip, multimodal recipe.

Reference anchors: components/models/nemotron_omni/model.py (towers +
RMSNorm→Linear→ReLU²→Linear projectors + placeholder scatter),
recipes/multimodal/finetune.py."""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.recipe

from automodel_tpu.models.audio import encoder as audio
from automodel_tpu.models.omni import model as omni

HF_OMNI = {
    "architectures": ["OmniForConditionalGeneration"],
    "image_token_id": 500,
    "audio_token_id": 501,
    "vision_config": {
        "image_size": 28, "patch_size": 14, "hidden_size": 24,
        "intermediate_size": 48, "num_hidden_layers": 2, "num_attention_heads": 4,
    },
    "audio_config": {
        "num_mel_bins": 20, "hidden_size": 16, "intermediate_size": 32,
        "num_hidden_layers": 2, "num_attention_heads": 2,
    },
    "text_config": {
        "architectures": ["LlamaForCausalLM"],
        "vocab_size": 512, "hidden_size": 32, "intermediate_size": 64,
        "num_hidden_layers": 2, "num_attention_heads": 4, "num_key_value_heads": 2,
    },
}


def _cfg():
    return omni.omni_config(HF_OMNI, dtype=jnp.float32, remat_policy="none")


def test_audio_encoder_shapes_and_mask():
    cfg = audio.AudioConfig(
        num_mel_bins=20, hidden_size=16, intermediate_size=32,
        num_layers=2, num_heads=2, dtype=jnp.float32, remat_policy="none",
    )
    params = audio.init(cfg, jax.random.key(0))
    mel = jax.random.normal(jax.random.key(1), (2, 32, 20))
    out, mask = audio.forward(params, cfg, mel)
    assert out.shape == (2, 8, 16)  # ×4 time subsample
    assert bool(mask.all())
    assert np.isfinite(np.asarray(out)).all()

    # padding isolation: frames beyond the valid length must not change
    # the valid frames' outputs
    fm = jnp.asarray([[True] * 16 + [False] * 16, [True] * 32])
    out1, m1 = audio.forward(params, cfg, mel, fm)
    mel2 = mel.at[0, 16:].set(123.0)  # corrupt only padded frames of row 0
    out2, _ = audio.forward(params, cfg, mel2, fm)
    np.testing.assert_allclose(
        np.asarray(out1[0, :4]), np.asarray(out2[0, :4]), rtol=1e-5, atol=1e-5
    )
    assert not np.asarray(m1)[0, 4:].any() and np.asarray(m1)[0, :4].all()


def test_omni_forward_audio_and_image_reach_logits():
    cfg = _cfg()
    params = omni.init(cfg, jax.random.key(0))
    n_img = cfg.vision.num_patches
    n_aud = cfg.audio.out_frames(16)
    ids = jnp.concatenate([
        jnp.full((1, n_img), 500, jnp.int32),
        jnp.full((1, n_aud), 501, jnp.int32),
        jnp.arange(8, dtype=jnp.int32)[None, :] + 1,
    ], axis=1)
    img = jax.random.normal(jax.random.key(1), (1, 28, 28, 3))
    mel = jax.random.normal(jax.random.key(2), (1, 16, 20))
    base = omni.forward(params, cfg, ids, img, mel)
    assert base.shape == (1, n_img + n_aud + 8, 512)
    # perturbing the audio changes logits; likewise the image
    a2 = omni.forward(params, cfg, ids, img, mel + 1.0)
    i2 = omni.forward(params, cfg, ids, img + 1.0, mel)
    assert not np.allclose(np.asarray(base), np.asarray(a2))
    assert not np.allclose(np.asarray(base), np.asarray(i2))
    # text-only path runs without media
    t = omni.forward(params, cfg, ids)
    assert np.isfinite(np.asarray(t)).all()


def test_omni_adapter_roundtrip(tmp_path):
    from automodel_tpu.checkpoint import (
        HFCheckpointReader,
        get_adapter,
        save_hf_checkpoint,
    )
    from automodel_tpu.models.registry import get_model_spec

    spec = get_model_spec(HF_OMNI)
    cfg = spec.config_from_hf(HF_OMNI, dtype=jnp.float32, remat_policy="none")
    params = spec.module.init(cfg, jax.random.key(3))
    adapter = get_adapter(spec.adapter_name, cfg)
    save_hf_checkpoint(adapter.to_hf(params), str(tmp_path), hf_config=HF_OMNI)
    reader = HFCheckpointReader(str(tmp_path))
    assert "sound_projection.linear1.weight" in reader.keys()
    assert "sound_encoder.encoder.layers.0.mlp.fc1.weight" in reader.keys()
    assert "vision_projection.norm.weight" in reader.keys()
    restored = adapter.from_hf(reader)
    for (pa, a), (pb, b) in zip(
        sorted(jax.tree_util.tree_leaves_with_path(params), key=lambda t: str(t[0])),
        sorted(jax.tree_util.tree_leaves_with_path(restored), key=lambda t: str(t[0])),
    ):
        assert str(pa) == str(pb)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), err_msg=str(pa))


@pytest.mark.slow  # compile-heavy recipe; omni fwd/adapter tests stay tier-1
def test_multimodal_recipe_trains(tmp_path):
    from automodel_tpu.cli.app import resolve_recipe_class
    from automodel_tpu.config import ConfigNode

    cfg = ConfigNode({
        "seed": 5,
        "recipe": "multimodal_finetune",
        "run_dir": str(tmp_path),
        "auto_resume": False,
        "model": {"hf_config": HF_OMNI, "dtype": "float32", "remat_policy": "none"},
        "distributed": {"dp_shard": -1},
        "freeze_audio_tower": True,
        "dataset": {
            "_target_": "automodel_tpu.datasets.audio.MockOmniDatasetConfig",
            "num_samples": 32, "seq_len": 32, "vocab_size": 512,
            "image_size": 28, "patch_size": 14, "image_token_id": 500,
            "audio_frames": 16, "num_mel_bins": 20, "audio_token_id": 501,
        },
        "dataloader": {"microbatch_size": 8, "grad_acc_steps": 1},
        "optimizer": {"name": "adamw", "lr": 1e-3, "weight_decay": 0.0},
        "lr_scheduler": {"style": "constant", "warmup_steps": 0},
        "step_scheduler": {"max_steps": 3, "ckpt_every_steps": 100},
        "checkpoint": {"enabled": False},
        "loss": {"chunk_size": 32},
    })
    recipe_cls = resolve_recipe_class(cfg)
    assert recipe_cls.__name__ == "FinetuneRecipeForOmni"
    r = recipe_cls(cfg)
    r.setup()
    at_before = jax.tree.map(
        lambda x: np.asarray(x).copy(), r.train_state.params["audio_tower"]
    )
    sp_before = jax.tree.map(
        lambda x: np.asarray(x).copy(), r.train_state.params["sound_projection"]
    )
    r.run_train_validation_loop()
    recs = [json.loads(l) for l in open(tmp_path / "training.jsonl")]
    assert len(recs) == 3 and all(np.isfinite(x["loss"]) for x in recs)
    # frozen audio tower unchanged; the sound projector actually moved
    for a, b in zip(jax.tree.leaves(at_before),
                    jax.tree.leaves(r.train_state.params["audio_tower"])):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    moved = any(
        not np.allclose(np.asarray(a), np.asarray(b))
        for a, b in zip(
            jax.tree.leaves(sp_before),
            jax.tree.leaves(r.train_state.params["sound_projection"]),
        )
    )
    assert moved, "sound_projection did not train"
