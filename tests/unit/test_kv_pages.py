"""Paged KV pool: allocator accounting, page tables, defrag compaction."""

import jax
import jax.numpy as jnp
import numpy as np

from automodel_tpu.serving.kv_pages import (
    PageAllocator,
    apply_defrag,
    init_pool,
    pages_for,
    pool_trash_index,
)


def test_pages_for():
    assert pages_for(1, 4) == 1
    assert pages_for(4, 4) == 1
    assert pages_for(5, 4) == 2
    assert pages_for(0, 4) == 0


def test_alloc_grow_free_accounting():
    a = PageAllocator(num_pages=6, page_size=4)
    assert a.num_free == 6
    assert a.ensure(0, 5)            # 2 pages
    assert a.ensure(1, 9)            # 3 pages
    assert a.num_free == 1
    assert len(a.table(0)) == 2 and len(a.table(1)) == 3
    # growth within the covered range allocates nothing
    assert a.ensure(0, 8) and len(a.table(0)) == 2
    # dense-prefix tables: pages are appended, never reordered
    t0 = list(a.table(0))
    assert a.ensure(0, 12) and a.table(0)[:2] == t0
    assert a.num_free == 0
    # exhausted: refuse WITHOUT partial allocation
    assert not a.ensure(1, 16)
    assert len(a.table(1)) == 3 and a.num_free == 0
    a.free_slot(0)
    assert a.num_free == 3 and a.table(0) == []
    # no double-free surprises: every page accounted exactly once
    a.free_slot(1)
    assert sorted(a._free) == list(range(6))


def test_defrag_compacts_live_pages():
    a = PageAllocator(num_pages=8, page_size=2)
    a.ensure(0, 4)   # 2 pages
    a.ensure(1, 4)   # 2 pages
    a.ensure(2, 2)   # 1 page
    a.free_slot(1)   # holes in the middle
    live_before = {s: list(a.table(s)) for s in (0, 2)}
    plan = a.defrag_plan()
    assert plan is not None
    src, n_live = plan
    assert n_live == 3
    # tables now a dense prefix, contents preserved through the mapping
    used = sorted(p for s in (0, 2) for p in a.table(s))
    assert used == [0, 1, 2]
    assert a.num_free == 5
    # device-side: new page i holds old page src[i] (apply_defrag donates
    # the pool, so compare against a host snapshot taken before the call)
    pool = (jnp.arange(2 * 9 * 2 * 1 * 1, dtype=jnp.float32).reshape(2, 9, 2, 1, 1),)
    before = np.asarray(pool[0])
    moved = apply_defrag(pool, src)[0]
    for slot in (0, 2):
        for old, new in zip(live_before[slot], a.table(slot)):
            np.testing.assert_array_equal(
                np.asarray(moved[:, new]), before[:, old]
            )
    # trash page (index num_pages) stays put
    np.testing.assert_array_equal(np.asarray(moved[:, 8]), before[:, 8])


def test_refcount_share_and_free():
    """A page adopted into a second table frees only when the LAST
    reference drops; incref/decref pin pages without any table."""
    a = PageAllocator(num_pages=4, page_size=2)
    a.ensure(0, 4)                      # slot 0: 2 pages
    shared = list(a.table(0))
    a.adopt(1, shared)                  # slot 1 maps the same pages
    assert a.table(1) == shared
    assert all(a.refcount(p) == 2 for p in shared)
    a.free_slot(0)
    assert a.num_free == 2              # nothing freed: slot 1 still reads
    assert all(a.refcount(p) == 1 for p in shared)
    a.incref(shared[0])                 # radix-tree style pin
    a.free_slot(1)
    assert a.num_free == 3 and a.refcount(shared[0]) == 1
    a.decref(shared[0])
    assert a.num_free == 4


def test_cow_splits_shared_page():
    a = PageAllocator(num_pages=4, page_size=2)
    a.ensure(0, 4)
    a.adopt(1, list(a.table(0)))
    old = a.table(1)[1]
    pair = a.cow(1, 1)
    assert pair is not None and pair[0] == old
    src, dst = pair
    assert a.table(1)[1] == dst and a.table(0)[1] == old
    assert a.refcount(old) == 1 and a.refcount(dst) == 1
    # exclusive page → write in place, no copy
    assert a.cow(1, 1) is None


def test_ensure_reclaims_behind_free_list():
    """The reclaim hook is consulted only once the free list is short."""
    calls = []
    a = PageAllocator(num_pages=3, page_size=2)

    def reclaim(n):
        calls.append(n)
        return 0

    assert a.ensure(0, 4, reclaim=reclaim)   # 2 pages, free list suffices
    assert calls == []
    assert not a.ensure(0, 8, reclaim=reclaim)  # needs 2 more, 1 free
    assert calls == [1]


def test_defrag_moves_shared_page_once_and_patches_every_table():
    """A multiply-referenced page gets ONE mapping entry (one device copy)
    while every referencing table — and any remap listener, i.e. the radix
    tree — sees the new index."""
    a = PageAllocator(num_pages=8, page_size=2)
    a.ensure(0, 4)                      # slot 0: pages 0, 1
    a.ensure(2, 4)                      # slot 2: pages 2, 3
    a.adopt(1, list(a.table(0)))        # slot 1 shares 0, 1
    a.ensure(1, 6)                      # + one private page (4)
    a.free_slot(2)                      # holes at 2, 3
    seen = []
    a.register_remap_listener(seen.append)
    plan = a.defrag_plan()
    assert plan is not None
    src, n_live = plan
    assert n_live == 3                  # 2 shared (once each) + 1 private
    assert a.table(0) == a.table(1)[:2]  # sharing survives the move
    assert sorted({p for t in (a.table(0), a.table(1)) for p in t}) == [0, 1, 2]
    (mapping,) = seen
    assert sorted(mapping.values()) == [0, 1, 2]
    # shared pages keep their refcounts under the new numbering
    assert all(a.refcount(p) == 2 for p in a.table(0))
    assert a.num_free == 5


def test_truncate_drops_provisional_tail():
    """Speculative rollback: truncate() releases exclusively-held tail
    pages to the free list, but a SHARED tail page survives for its other
    holder (only the truncating slot's reference drops)."""
    a = PageAllocator(num_pages=8, page_size=2)
    a.ensure(0, 8)                 # 4 pages
    assert a.truncate(0, 2) == 2   # drop 2 exclusive provisional pages
    assert len(a.table(0)) == 2 and a.num_free == 6
    # shared tail: slot 1 adopts slot 0's pages, then truncates them away
    a.adopt(1, list(a.table(0)))
    assert a.truncate(1, 0) == 2
    assert a.num_free == 6         # slot 0 still references both pages
    assert all(a.refcount(p) == 1 for p in a.table(0))
    assert a.truncate(0, 2) == 0   # no-op at or below the target length
    a.free_slot(0)
    assert a.num_free == 8


def test_defrag_noop_when_compact():
    a = PageAllocator(num_pages=4, page_size=2)
    a.ensure(0, 4)
    assert a.defrag_plan() is None


def test_init_pool_shapes():
    from automodel_tpu.models.llm.decoder import TransformerConfig

    cfg = TransformerConfig(
        vocab_size=8, hidden_size=16, intermediate_size=16, num_layers=2,
        num_heads=4, num_kv_heads=2, dtype=jnp.float32, remat_policy="none",
    )
    pool = init_pool(cfg, [2], num_pages=6, page_size=4)
    (k, v), = pool
    D = cfg.resolved_head_dim
    assert k.shape == (2, 7, 4, 2, D) and v.shape == k.shape  # N+1 pages
    assert pool_trash_index(pool) == 6

    import dataclasses

    mla = dataclasses.replace(
        cfg, attention_type="mla", mla_kv_lora_rank=8, mla_q_lora_rank=0,
        mla_qk_nope_head_dim=4, mla_qk_rope_head_dim=4, mla_v_head_dim=4,
    )
    (c, kr), = init_pool(mla, [2], num_pages=6, page_size=4)
    assert c.shape == (2, 7, 4, 8) and kr.shape == (2, 7, 4, 4)
