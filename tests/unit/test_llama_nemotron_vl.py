"""Llama-Nemotron VL: SigLIP tower + pixel-shuffle + mlp1 + bidirectional
llama retrieval embeddings (reference: models/llama_nemotron_vl/model.py).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from automodel_tpu.models.registry import get_model_spec
from automodel_tpu.models.vlm import llama_nemotron_vl as lnv

LNV_HF = {
    "architectures": ["LlamaNemotronVLModel"],
    "model_type": "llama_nemotron_vl",
    "img_context_token_id": 120,
    "downsample_ratio": 0.5,
    "select_layer": -1,
    "pooling": "avg",
    "vision_config": {
        "model_type": "siglip_vision_model",
        "hidden_size": 32, "intermediate_size": 48, "num_hidden_layers": 2,
        "num_attention_heads": 2, "image_size": 56, "patch_size": 14,
        "hidden_act": "gelu_pytorch_tanh",
    },
    "llm_config": {
        "architectures": ["LlamaBidirectionalModel"],
        "vocab_size": 128, "hidden_size": 32, "intermediate_size": 64,
        "num_hidden_layers": 2, "num_attention_heads": 4,
        "num_key_value_heads": 2, "pooling": "avg",
    },
}


def _setup():
    spec = get_model_spec(LNV_HF)
    cfg = spec.config_from_hf(LNV_HF, dtype=jnp.float32, remat_policy="none")
    return spec, cfg, lnv.init(cfg, jax.random.key(0))


def _batch(cfg, B=2, S=24):
    n_img = cfg.num_image_token  # (56/14)² · 0.25 = 4
    rng = np.random.default_rng(0)
    text = rng.integers(1, 100, (B, S - n_img), dtype=np.int32)
    ids = np.concatenate(
        [text[:, :3], np.full((B, n_img), 120, np.int32), text[:, 3:]], axis=1
    )
    pixels = rng.normal(size=(B, 56, 56, 3)).astype(np.float32)
    return jnp.asarray(ids), jnp.asarray(pixels)


def test_config_and_token_count():
    spec, cfg, params = _setup()
    assert cfg.text.causal is False
    assert cfg.num_image_token == 4
    assert cfg.vision.use_cls_token is False
    r = int(1 / cfg.downsample_ratio)
    assert params["mlp1"]["norm"]["scale"].shape == (32 * r * r,)


def test_pixel_shuffle_is_exact_space_to_depth():
    """Pinned to the reference view/permute sequence (model.py:627)."""
    x = jnp.arange(1 * 4 * 4 * 2, dtype=jnp.float32).reshape(1, 4, 4, 2)
    y = lnv.pixel_shuffle(x, 0.5)
    assert y.shape == (1, 2, 2, 8)
    xs = np.asarray(x)

    # replicate torch view/permute/contiguous-view semantics with numpy
    t = xs.reshape(1, 4, 2, 4)            # view(n, w, h*s, c/s)
    t = np.transpose(t, (0, 2, 1, 3))     # permute
    t = np.ascontiguousarray(t).reshape(1, 2, 2, 8)
    t = np.transpose(t, (0, 2, 1, 3))
    np.testing.assert_array_equal(np.asarray(y), t)


@pytest.mark.slow
def test_forward_and_embed():
    spec, cfg, params = _setup()
    ids, pixels = _batch(cfg)
    hidden = lnv.forward(params, cfg, ids, pixels)
    assert hidden.shape == (2, 24, 32)
    assert np.isfinite(np.asarray(hidden)).all()
    # image changes the embedding
    mask = jnp.ones(ids.shape, jnp.int32)
    e1 = lnv.embed(params, cfg, ids, pixels, mask)
    e2 = lnv.embed(params, cfg, ids, pixels + 1.0, mask)
    assert e1.shape == (2, 32)
    assert np.abs(np.asarray(e1) - np.asarray(e2)).max() > 1e-6
    # pooling variants
    assert lnv.embed(params, cfg, ids, pixels, mask, pooling="last").shape == (2, 32)
    assert lnv.embed(params, cfg, ids, pixels, mask, pooling="cls").shape == (2, 32)


@pytest.mark.slow
def test_bidirectional_attention():
    """Non-causal: a change in a LATE token influences an EARLY position's
    hidden state (impossible under causal masking)."""
    spec, cfg, params = _setup()
    ids, pixels = _batch(cfg, B=1)
    h1 = lnv.forward(params, cfg, ids, pixels)
    ids2 = ids.at[0, -1].set(int(ids[0, -1]) % 100 + 1)
    h2 = lnv.forward(params, cfg, ids2, pixels)
    assert np.abs(np.asarray(h1[0, 0]) - np.asarray(h2[0, 0])).max() > 1e-7


@pytest.mark.slow
def test_adapter_roundtrip():
    from automodel_tpu.checkpoint.hf_adapter import get_adapter

    spec, cfg, params = _setup()
    ad = get_adapter(spec.adapter_name, cfg, **spec.adapter_kwargs)
    sd = dict(ad.to_hf(params))
    assert "vision_model.vision_model.embeddings.patch_embedding.weight" in sd
    assert sd["mlp1.0.weight"].shape == (128,)   # LN over 4·Hv
    assert sd["mlp1.1.weight"].shape == (32, 128)
    assert "language_model.embed_tokens.weight" in sd      # bare LlamaModel
    assert "language_model.model.embed_tokens.weight" not in sd
    assert not any("lm_head" in k for k in sd)
    p2 = ad.from_hf(lambda k: np.asarray(sd[k]))
    # checkpoint has no head → restore drops the leaf; compare hidden states
    p2["language_model"]["lm_head"] = params["language_model"]["lm_head"]
    ids, pixels = _batch(cfg, B=1)
    h1 = lnv.forward(params, cfg, ids, pixels)
    h2 = lnv.forward(jax.tree.map(jnp.asarray, p2), cfg, ids, pixels)
    np.testing.assert_allclose(np.asarray(h1), np.asarray(h2), atol=1e-5)
