"""AutoDiffusionPipeline + VAE tier.

Reference anchor: _diffusers/auto_diffusion_pipeline.py (973 LoC) — the
diffusers-layout pipeline loader; diffusers AutoencoderKL for the VAE
semantics (scaling_factor, posterior sampling)."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.recipe

from automodel_tpu.diffusion.pipeline import AutoDiffusionPipeline, SchedulerConfig
from automodel_tpu.models.diffusion import dit, vae

DIT_CFG = dit.DiTConfig(
    input_size=8, patch_size=2, in_channels=4, hidden_size=32,
    num_layers=2, num_heads=4, num_classes=3,
    dtype=jnp.float32, remat_policy="none",
)
VAE_CFG = vae.VAEConfig(
    in_channels=3, latent_channels=4, base_channels=16, channel_mults=(1, 2),
    num_res_blocks=1, groups=4, dtype=jnp.float32,
)


def test_vae_encode_decode_shapes_and_grad():
    params = vae.init(VAE_CFG, jax.random.key(0))
    img = jax.random.normal(jax.random.key(1), (2, 16, 16, 3))
    z = vae.encode(params, VAE_CFG, img)
    assert z.shape == (2, 8, 8, 4)  # one stride-2 level
    out = vae.decode(params, VAE_CFG, z)
    assert out.shape == (2, 16, 16, 3)
    assert np.isfinite(np.asarray(out)).all()
    # posterior sampling differs from the mean path
    z2 = vae.encode(params, VAE_CFG, img, rng=jax.random.key(2))
    assert not np.allclose(np.asarray(z), np.asarray(z2))
    # reconstruction loss is differentiable end to end
    g = jax.grad(
        lambda p: jnp.mean((vae.decode(p, VAE_CFG, vae.encode(p, VAE_CFG, img)) - img) ** 2)
    )(params)
    assert all(np.isfinite(np.asarray(x)).all() for x in jax.tree.leaves(g))


def test_pipeline_save_load_sample_roundtrip(tmp_path):
    tparams = dit.init(DIT_CFG, jax.random.key(0))
    vparams = vae.init(VAE_CFG, jax.random.key(1))
    pipe = AutoDiffusionPipeline(
        transformer_cfg=DIT_CFG, transformer_params=tparams,
        scheduler=SchedulerConfig(shift=2.0),
        vae_cfg=VAE_CFG, vae_params=vparams,
    )
    out = str(tmp_path / "pipe")
    pipe.save_pretrained(out)
    # diffusers layout on disk
    index = json.loads(open(os.path.join(out, "model_index.json")).read())
    assert "transformer" in index and "vae" in index
    assert os.path.exists(os.path.join(out, "transformer", "model.safetensors"))
    assert os.path.exists(os.path.join(out, "scheduler", "scheduler_config.json"))

    loaded = AutoDiffusionPipeline.from_pretrained(out)
    assert loaded.scheduler.shift == 2.0
    assert loaded.transformer_cfg.num_classes == 3
    for a, b in zip(jax.tree.leaves(tparams), jax.tree.leaves(loaded.transformer_params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))

    # sampling: CFG path decodes through the VAE to image space
    labels = jnp.asarray([0, 2])
    imgs = loaded(
        jax.random.key(3), batch_size=2, class_labels=labels,
        guidance_scale=2.0, num_inference_steps=3,
    )
    assert imgs.shape == (2, 16, 16, 3)
    assert np.isfinite(np.asarray(imgs)).all()
    # latent-only path
    lat = loaded(jax.random.key(3), batch_size=2, decode=False,
                 num_inference_steps=2)
    assert lat.shape == (2, 8, 8, 4)


def test_diffusion_recipe_exports_pipeline(tmp_path):
    """End-to-end: train the DiT recipe briefly, export, reload, sample."""
    from automodel_tpu.cli.app import resolve_recipe_class
    from automodel_tpu.config import ConfigNode

    cfg = ConfigNode({
        "seed": 3,
        "recipe": "diffusion_train",
        "run_dir": str(tmp_path),
        "auto_resume": False,
        "dit": {
            "input_size": 8, "patch_size": 2, "in_channels": 4,
            "hidden_size": 32, "num_layers": 2, "num_heads": 4,
            "num_classes": 3, "dtype": "float32", "remat_policy": "none",
        },
        "flow_matching": {"shift": 2.0, "cfg_drop_prob": 0.2},
        "distributed": {"dp_shard": -1},
        "dataset": {
            "_target_": "automodel_tpu.datasets.mock.MockLatentDatasetConfig",
            "num_samples": 32, "latent_size": 8, "channels": 4, "num_classes": 3,
        },
        "dataloader": {"microbatch_size": 8, "grad_acc_steps": 1},
        "optimizer": {"name": "adamw", "lr": 1e-3, "weight_decay": 0.0},
        "lr_scheduler": {"style": "constant", "warmup_steps": 0},
        "step_scheduler": {"max_steps": 2, "ckpt_every_steps": 100},
        "checkpoint": {"enabled": False},
    })
    r = resolve_recipe_class(cfg)(cfg)
    r.setup()
    r.run_train_validation_loop()
    out = r.save_consolidated_hf()
    pipe = AutoDiffusionPipeline.from_pretrained(out)
    lat = pipe(jax.random.key(0), batch_size=2, decode=False, num_inference_steps=2)
    assert lat.shape == (2, 8, 8, 4)
    assert np.isfinite(np.asarray(lat)).all()
