"""Kill-and-resume: SIGTERM a REAL subprocess trainer mid-run, restart it
with auto_resume, and assert the concatenated loss curve is step-for-step
identical to an uninterrupted run.

This pins the end-to-end resume claims (training/rng.py key-stream counter,
step_scheduler/dataloader positions through the checkpoint extra side-car,
the SIGTERM → emergency-checkpoint path) that the in-process tests can only
check piecewise: the resumed process rebuilds everything from disk.
"""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest
import yaml

pytestmark = pytest.mark.recipe

STEPS = 16


def _cfg(workdir: str) -> dict:
    return {
        "seed": 13,
        "run_dir": os.path.join(workdir, "run"),
        "auto_resume": True,
        "model": {
            "hf_config": {
                "architectures": ["LlamaForCausalLM"],
                "vocab_size": 128, "hidden_size": 32, "intermediate_size": 64,
                "num_hidden_layers": 2, "num_attention_heads": 4,
                "num_key_value_heads": 2,
            },
            "dtype": "float32",
            "remat_policy": "none",
        },
        "distributed": {"dp_shard": -1},
        "dataset": {
            "_target_": "automodel_tpu.datasets.mock.MockDatasetConfig",
            "num_samples": 1024, "seq_len": 128, "vocab_size": 128,
        },
        "dataloader": {"microbatch_size": 8, "grad_acc_steps": 2},
        "optimizer": {"name": "adamw", "lr": 1e-3, "weight_decay": 0.0},
        "lr_scheduler": {"warmup_steps": 2, "decay_steps": STEPS, "style": "cosine"},
        "step_scheduler": {
            "max_steps": STEPS, "ckpt_every_steps": 1000, "num_epochs": 4,
        },
        "checkpoint": {
            "enabled": True,
            "checkpoint_dir": os.path.join(workdir, "ckpt"),
            "async_save": True,
        },
        "resilience": {"sigterm_grace_s": 120.0},
        "loss": {"chunk_size": 128},
    }


def _launch(cfg: dict, workdir: str, name: str):
    path = os.path.join(workdir, f"{name}.yaml")
    with open(path, "w") as f:
        yaml.safe_dump(cfg, f)
    env = os.environ.copy()
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    log = open(os.path.join(workdir, f"{name}.log"), "w")
    proc = subprocess.Popen(
        [sys.executable, "-m", "automodel_tpu", path],
        env=env, stdout=log, stderr=subprocess.STDOUT,
    )
    return proc, log


def _records(run_dir: str) -> list:
    path = os.path.join(run_dir, "training.jsonl")
    if not os.path.exists(path):
        return []
    return [json.loads(l) for l in open(path) if l.strip()]


def _losses(recs) -> dict:
    return {r["step"]: r["loss"] for r in recs if "loss" in r and "step" in r}


def _tail(workdir, name):
    return open(os.path.join(workdir, f"{name}.log")).read()[-2000:]


def test_sigterm_kill_and_resume_reproduces_uninterrupted_curve(tmp_path):
    work = str(tmp_path)

    # the uninterrupted golden runs CONCURRENTLY in its own directories (it
    # shares nothing with the preempted pair); joined before the comparison
    gwork = os.path.join(work, "golden")
    os.makedirs(gwork)
    gcfg = _cfg(gwork)
    p3, log3 = _launch(gcfg, gwork, "golden")

    # 1) the run that gets preempted: wait for a few real steps, SIGTERM it
    cfg = _cfg(work)
    p1, log1 = _launch(cfg, work, "interrupted")
    run_dir = cfg["run_dir"]
    deadline = time.monotonic() + 420
    try:
        while time.monotonic() < deadline and p1.poll() is None:
            if len(_losses(_records(run_dir))) >= 3:
                break
            time.sleep(0.02)
        assert p1.poll() is None, (
            f"trainer finished before it could be killed:\n{_tail(work, 'interrupted')}"
        )
        p1.send_signal(signal.SIGTERM)
        p1.wait(timeout=300)
    finally:
        log1.close()
    assert p1.returncode == 0, (
        f"SIGTERM'd trainer exited rc={p1.returncode}:\n{_tail(work, 'interrupted')}"
    )
    recs1 = _records(run_dir)
    killed_at = max(_losses(recs1))
    assert 0 < killed_at < STEPS, f"run was not interrupted mid-run: {killed_at}"
    ev = [r for r in recs1 if r.get("event") == "emergency_checkpoint"]
    assert ev and ev[0]["committed"], "emergency checkpoint did not commit"

    # 2) fresh process, same config: auto_resume from the emergency ckpt
    p2, log2 = _launch(cfg, work, "resumed")
    try:
        p2.wait(timeout=420)
    finally:
        log2.close()
    assert p2.returncode == 0, f"resumed trainer failed:\n{_tail(work, 'resumed')}"
    merged = _losses(_records(run_dir))  # same jsonl, appended
    assert sorted(merged) == list(range(1, STEPS + 1)), sorted(merged)
    resumed_recs = [
        r for r in _records(run_dir) if r.get("step") == killed_at + 1 and "loss" in r
    ]
    assert any("time_to_resume_s" in r for r in resumed_recs)

    # 3) join the uninterrupted golden
    try:
        p3.wait(timeout=420)
    finally:
        log3.close()
    assert p3.returncode == 0, f"golden trainer failed:\n{_tail(gwork, 'golden')}"
    golden = _losses(_records(gcfg["run_dir"]))
    assert sorted(golden) == list(range(1, STEPS + 1))

    # the concatenated curve must be step-for-step identical: same data
    # order (dataloader position), same per-step rng keys (counter), same
    # optimizer state (orbax round-trip) ⇒ same floats on the same machine
    a = np.array([merged[s] for s in range(1, STEPS + 1)])
    b = np.array([golden[s] for s in range(1, STEPS + 1)])
    np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-7)
