"""VLM tier: ViT encoder, llava merge, recipe, HF adapter roundtrip."""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.recipe

from automodel_tpu.checkpoint import HFCheckpointReader, get_adapter, save_hf_checkpoint
from automodel_tpu.models.vision import vit
from automodel_tpu.models.vlm import llava

HF_VLM = {
    "architectures": ["LlavaForConditionalGeneration"],
    "image_token_index": 500,
    "vision_config": {
        "image_size": 28, "patch_size": 14, "hidden_size": 24,
        "intermediate_size": 48, "num_hidden_layers": 2, "num_attention_heads": 4,
    },
    "text_config": {
        "architectures": ["LlamaForCausalLM"],
        "vocab_size": 512, "hidden_size": 32, "intermediate_size": 64,
        "num_hidden_layers": 2, "num_attention_heads": 4, "num_key_value_heads": 2,
    },
}


def _cfg():
    return llava.llava_config(HF_VLM, dtype=jnp.float32, remat_policy="none")


def test_vit_forward_and_permutation_invariance():
    cfg = vit.VisionConfig(
        image_size=28, patch_size=14, hidden_size=24, intermediate_size=48,
        num_layers=2, num_heads=4, dtype=jnp.float32, remat_policy="none",
    )
    params = vit.init(cfg, jax.random.key(0))
    img = jax.random.normal(jax.random.key(1), (2, 28, 28, 3))
    out = vit.forward(params, cfg, img)
    assert out.shape == (2, 4, 24)
    assert np.isfinite(np.asarray(out)).all()
    # different images → different features
    out2 = vit.forward(params, cfg, img + 1.0)
    assert not np.allclose(np.asarray(out), np.asarray(out2))


def test_merge_scatters_patches_in_order():
    tok = jnp.zeros((1, 6, 4))
    img = jnp.arange(12, dtype=jnp.float32).reshape(1, 3, 4)
    mask = jnp.asarray([[True, False, True, True, False, False]])
    merged = llava.merge_image_embeddings(tok, img, mask)
    np.testing.assert_array_equal(np.asarray(merged[0, 0]), np.asarray(img[0, 0]))
    np.testing.assert_array_equal(np.asarray(merged[0, 2]), np.asarray(img[0, 1]))
    np.testing.assert_array_equal(np.asarray(merged[0, 3]), np.asarray(img[0, 2]))
    np.testing.assert_array_equal(np.asarray(merged[0, 1]), 0.0)


def test_llava_forward_image_dependence():
    cfg = _cfg()
    params = llava.init(cfg, jax.random.key(0))
    n_img = cfg.vision.num_patches
    ids = jnp.concatenate(
        [jnp.full((1, n_img), 500, jnp.int32),
         jnp.arange(8, dtype=jnp.int32)[None, :] + 1], axis=1,
    )
    img1 = jax.random.normal(jax.random.key(1), (1, 28, 28, 3))
    img2 = img1 + 1.0
    l1 = llava.forward(params, cfg, ids, img1)
    l2 = llava.forward(params, cfg, ids, img2)
    assert l1.shape == (1, n_img + 8, 512)
    assert not np.allclose(np.asarray(l1), np.asarray(l2))  # image reaches logits


def test_llava_hf_roundtrip(tmp_path):
    cfg = _cfg()
    params = llava.init(cfg, jax.random.key(0))
    adapter = get_adapter("llava", cfg)
    save_hf_checkpoint(adapter.to_hf(params), str(tmp_path))
    reader = HFCheckpointReader(str(tmp_path))
    assert "language_model.model.embed_tokens.weight" in reader.keys()
    assert "multi_modal_projector.linear_1.weight" in reader.keys()
    assert "vision_tower.vision_model.encoder.layers.0.mlp.fc1.weight" in reader.keys()
    restored = adapter.from_hf(reader)
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(restored)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))


def test_vlm_recipe_trains(tmp_path):
    from automodel_tpu.cli.app import resolve_recipe_class
    from automodel_tpu.config import ConfigNode

    cfg = ConfigNode({
        "seed": 11,
        "recipe": "vlm_finetune",
        "run_dir": str(tmp_path),
        "auto_resume": False,
        "model": {"hf_config": HF_VLM, "dtype": "float32", "remat_policy": "none"},
        "distributed": {"dp_shard": -1},
        "freeze_vision_tower": True,
        "dataset": {
            "_target_": "automodel_tpu.datasets.vlm.MockVLMDatasetConfig",
            "num_samples": 64, "seq_len": 32, "vocab_size": 512,
            "image_size": 28, "patch_size": 14, "image_token_id": 500,
        },
        "dataloader": {"microbatch_size": 8, "grad_acc_steps": 1},
        "optimizer": {"name": "adamw", "lr": 1e-3, "weight_decay": 0.0},
        "lr_scheduler": {"style": "constant", "warmup_steps": 0},
        "step_scheduler": {"max_steps": 4, "ckpt_every_steps": 100},
        "checkpoint": {"enabled": False},
        "loss": {"chunk_size": 32},
    })
    recipe_cls = resolve_recipe_class(cfg)
    assert recipe_cls.__name__ == "FinetuneRecipeForVLM"
    r = recipe_cls(cfg)
    r.setup()
    vt_before = jax.tree.map(lambda x: np.asarray(x).copy(),
                             r.train_state.params["vision_tower"])
    r.run_train_validation_loop()
    recs = [json.loads(l) for l in open(tmp_path / "training.jsonl")]
    assert len(recs) == 4 and all(np.isfinite(x["loss"]) for x in recs)
    # frozen vision tower unchanged; language model moved
    for a, b in zip(jax.tree.leaves(vt_before),
                    jax.tree.leaves(r.train_state.params["vision_tower"])):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.slow  # teacher+student VLM compile; KD path stays tier-1 via llava_kd_smoke example
def test_vlm_kd_recipe_trains(tmp_path):
    """VLM distillation: frozen llava teacher → llava student, pixel
    values through BOTH forwards, fused hidden-space KD loss
    (reference: recipes/vlm/kd.py)."""
    from automodel_tpu.cli.app import resolve_recipe_class
    from automodel_tpu.config import ConfigNode

    cfg = ConfigNode({
        "seed": 13,
        "recipe": "vlm_kd",
        "run_dir": str(tmp_path),
        "auto_resume": False,
        "model": {"hf_config": HF_VLM, "dtype": "float32", "remat_policy": "none"},
        "teacher_model": {"hf_config": HF_VLM, "dtype": "float32"},
        "kd": {"ratio": 0.5, "temperature": 2.0},
        "distributed": {"dp_shard": -1},
        "dataset": {
            "_target_": "automodel_tpu.datasets.vlm.MockVLMDatasetConfig",
            "num_samples": 32, "seq_len": 32, "vocab_size": 512,
            "image_size": 28, "patch_size": 14, "image_token_id": 500,
        },
        "dataloader": {"microbatch_size": 8, "grad_acc_steps": 1},
        "optimizer": {"name": "adamw", "lr": 1e-3, "weight_decay": 0.0},
        "lr_scheduler": {"style": "constant", "warmup_steps": 0},
        "step_scheduler": {"max_steps": 3, "ckpt_every_steps": 100},
        "checkpoint": {"enabled": False},
        "loss": {"chunk_size": 32},
    })
    recipe_cls = resolve_recipe_class(cfg)
    assert recipe_cls.__name__ == "KDRecipeForVLM"
    r = recipe_cls(cfg)
    r.setup()
    t_before = jax.tree.map(lambda x: np.asarray(x).copy(), r.teacher_params)
    r.run_train_validation_loop()
    recs = [json.loads(l) for l in open(tmp_path / "training.jsonl")]
    assert len(recs) == 3 and all(np.isfinite(x["loss"]) for x in recs)
    # teacher untouched
    for a, b in zip(jax.tree.leaves(t_before), jax.tree.leaves(r.teacher_params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # student == teacher init? no — different seeds; KD loss at temperature
    # 2 with identical configs should still be finite and > 0
    assert recs[0]["loss"] > 0


def test_clip_style_tower_roundtrip(tmp_path):
    """CLIP variant: cls token, pre-LN, quick_gelu, penultimate feature layer."""
    hf = dict(HF_VLM)
    hf["vision_config"] = {**HF_VLM["vision_config"], "model_type": "clip_vision_model"}
    hf["vision_feature_layer"] = -2
    cfg = llava.llava_config(hf, dtype=jnp.float32, remat_policy="none")
    assert cfg.vision.use_cls_token and cfg.vision.use_pre_layernorm
    assert cfg.vision.activation == "quick_gelu" and cfg.vision.feature_layer == -2
    assert cfg.vision.num_positions == cfg.vision.num_patches + 1
    params = llava.init(cfg, jax.random.key(0))
    n_img = cfg.vision.num_patches
    ids = jnp.concatenate(
        [jnp.full((1, n_img), 500, jnp.int32),
         jnp.arange(8, dtype=jnp.int32)[None, :] + 1], axis=1,
    )
    img = jax.random.normal(jax.random.key(2), (1, 28, 28, 3))
    logits = llava.forward(params, cfg, ids, img)
    assert np.isfinite(np.asarray(logits)).all()

    adapter = get_adapter("llava", cfg)
    save_hf_checkpoint(adapter.to_hf(params), str(tmp_path))
    reader = HFCheckpointReader(str(tmp_path))
    assert "vision_tower.vision_model.embeddings.class_embedding" in reader.keys()
    assert "vision_tower.vision_model.pre_layrnorm.weight" in reader.keys()
    restored = adapter.from_hf(reader)
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(restored)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))


def test_vlm_flops_include_tower():
    cfg = _cfg()
    text_only = cfg.text.flops_per_token(64)
    assert cfg.flops_per_token(64) > text_only


@pytest.mark.slow
def test_llava_vlm_generate_matches_naive():
    """vlm_generate greedy == teacher-forced llava.forward argmax loop."""
    import numpy as np

    from automodel_tpu.inference.generate import GenerateConfig, vlm_generate
    from automodel_tpu.models.registry import get_model_spec
    from automodel_tpu.models.vlm import llava

    hf = {
        "architectures": ["LlavaForConditionalGeneration"],
        "model_type": "llava",
        "image_token_index": 120,
        "vision_config": {
            "model_type": "clip_vision_model", "hidden_size": 32,
            "intermediate_size": 64, "num_hidden_layers": 2,
            "num_attention_heads": 2, "image_size": 56, "patch_size": 14,
        },
        "text_config": {
            "architectures": ["LlamaForCausalLM"], "vocab_size": 128,
            "hidden_size": 32, "intermediate_size": 64,
            "num_hidden_layers": 2, "num_attention_heads": 4,
            "num_key_value_heads": 2,
        },
    }
    spec = get_model_spec(hf)
    cfg = spec.config_from_hf(hf, dtype=jnp.float32, remat_policy="none")
    params = llava.init(cfg, jax.random.key(0))
    rng = np.random.default_rng(0)
    ids = np.concatenate(
        [np.full((1, 16), 120, np.int32), rng.integers(1, 100, (1, 8), dtype=np.int32)],
        axis=1,
    )
    pix = rng.normal(size=(1, 56, 56, 3)).astype(np.float32)
    out = vlm_generate(
        llava, params, cfg, jnp.asarray(ids), jnp.asarray(pix),
        jax.random.key(1), GenerateConfig(max_new_tokens=4),
    )
    cur = jnp.asarray(ids)
    for _ in range(4):
        logits = llava.forward(params, cfg, cur, jnp.asarray(pix))
        nxt = jnp.argmax(logits[:, -1], -1).astype(jnp.int32)
        cur = jnp.concatenate([cur, nxt[:, None]], 1)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(cur))


@pytest.mark.recipe
def test_vlm_generate_recipe(tmp_path):
    """vlm_generate recipe: checkpoint-chassis reuse + generations.jsonl."""
    import json as _json

    from automodel_tpu.cli.app import resolve_recipe_class
    from automodel_tpu.config import ConfigNode

    cfg = ConfigNode({
        "recipe": "vlm_generate",
        "seed": 7,
        "run_dir": str(tmp_path),
        "auto_resume": False,
        "model": {
            "hf_config": {
                "architectures": ["LlavaForConditionalGeneration"],
                "model_type": "llava",
                "image_token_index": 500,
                "vision_config": {
                    "model_type": "clip_vision_model", "hidden_size": 32,
                    "intermediate_size": 64, "num_hidden_layers": 2,
                    "num_attention_heads": 2, "image_size": 56, "patch_size": 14,
                },
                "text_config": {
                    "architectures": ["LlamaForCausalLM"], "vocab_size": 512,
                    "hidden_size": 32, "intermediate_size": 64,
                    "num_hidden_layers": 2, "num_attention_heads": 4,
                    "num_key_value_heads": 2,
                },
            },
            "dtype": "float32", "remat_policy": "none",
        },
        "distributed": {"dp_shard": -1},
        "dataset": {
            "_target_": "automodel_tpu.datasets.vlm.MockVLMDatasetConfig",
            "num_samples": 8, "seq_len": 32, "vocab_size": 512,
            "image_size": 56, "patch_size": 14, "image_token_id": 500,
        },
        "dataloader": {"microbatch_size": 8, "grad_acc_steps": 1},
        "optimizer": {"lr": 1e-4},
        "lr_scheduler": {"style": "constant", "warmup_steps": 0},
        "step_scheduler": {"max_steps": 1},
        "checkpoint": {"enabled": False},
        "generation": {"max_new_tokens": 4},
        "max_batches": 1,
    })
    r = resolve_recipe_class(cfg)(cfg)
    r.setup()
    r.run_train_validation_loop()
    recs = [_json.loads(l) for l in open(tmp_path / "generations.jsonl") if l.strip()]
    assert len(recs) == 8
    assert all(len(x["generated_ids"]) == 4 for x in recs)
