"""KV-cache generation parity vs full re-forward."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from automodel_tpu.inference.generate import GenerateConfig, generate
from automodel_tpu.models.llm import decoder
from automodel_tpu.models.llm.decoder import TransformerConfig

CFG = TransformerConfig(
    vocab_size=64, hidden_size=32, intermediate_size=48, num_layers=2,
    num_heads=4, num_kv_heads=2, qk_norm=True, dtype=jnp.float32,
    remat_policy="none",
)


def _naive_greedy(params, cfg, ids, n):
    """Reference: re-run the full forward for every new token."""
    for _ in range(n):
        logits = decoder.forward(params, cfg, ids)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        ids = jnp.concatenate([ids, nxt[:, None]], axis=1)
    return ids


@pytest.mark.slow
def test_greedy_matches_naive():
    params = decoder.init(CFG, jax.random.key(0))
    prompt = jax.random.randint(jax.random.key(1), (2, 7), 0, 64)
    fast = generate(params, CFG, prompt, jax.random.key(2), GenerateConfig(max_new_tokens=6))
    slow = _naive_greedy(params, CFG, prompt, 6)
    np.testing.assert_array_equal(np.asarray(fast), np.asarray(slow))


def test_single_new_token():
    params = decoder.init(CFG, jax.random.key(0))
    prompt = jax.random.randint(jax.random.key(3), (1, 5), 0, 64)
    out = generate(params, CFG, prompt, jax.random.key(4), GenerateConfig(max_new_tokens=1))
    slow = _naive_greedy(params, CFG, prompt, 1)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(slow))


def test_temperature_sampling_valid_and_varied():
    params = decoder.init(CFG, jax.random.key(0))
    prompt = jax.random.randint(jax.random.key(5), (1, 4), 0, 64)
    g = GenerateConfig(max_new_tokens=8, temperature=1.0)
    a = generate(params, CFG, prompt, jax.random.key(6), g)
    b = generate(params, CFG, prompt, jax.random.key(7), g)
    assert ((np.asarray(a) >= 0) & (np.asarray(a) < 64)).all()
    assert not np.array_equal(np.asarray(a), np.asarray(b))  # keys differ


@pytest.mark.slow
def test_mla_matches_naive():
    """MLA absorbed latent-cache decode == full re-forward (VERDICT r3 #9:
    the MLA decode path previously raised NotImplementedError)."""
    import dataclasses

    cfg = dataclasses.replace(
        CFG, attention_type="mla", mla_kv_lora_rank=16, mla_q_lora_rank=12,
        mla_qk_nope_head_dim=8, mla_qk_rope_head_dim=8, mla_v_head_dim=8,
    )
    params = decoder.init(cfg, jax.random.key(0))
    prompt = jax.random.randint(jax.random.key(12), (2, 7), 0, 64)
    fast = generate(params, cfg, prompt, jax.random.key(2), GenerateConfig(max_new_tokens=6))
    slow = _naive_greedy(params, cfg, prompt, 6)
    np.testing.assert_array_equal(np.asarray(fast), np.asarray(slow))


@pytest.mark.slow
def test_mla_sliding_window_matches_naive():
    """MLA decode honors per-layer sliding windows (the training forward
    does; decode must not silently widen to global)."""
    import dataclasses

    cfg = dataclasses.replace(
        CFG, attention_type="mla", mla_kv_lora_rank=16, mla_q_lora_rank=12,
        mla_qk_nope_head_dim=8, mla_qk_rope_head_dim=8, mla_v_head_dim=8,
        sliding_window=4,
    )
    params = decoder.init(cfg, jax.random.key(0))
    prompt = jax.random.randint(jax.random.key(14), (2, 7), 0, 64)
    fast = generate(params, cfg, prompt, jax.random.key(2), GenerateConfig(max_new_tokens=6))
    slow = _naive_greedy(params, cfg, prompt, 6)
    np.testing.assert_array_equal(np.asarray(fast), np.asarray(slow))


@pytest.mark.slow
def test_moe_mla_matches_naive():
    """DeepSeek-family shape: first_k_dense + MoE stack + MLA cache."""
    from automodel_tpu.models.moe_lm import decoder as moe_decoder
    from automodel_tpu.models.moe_lm.decoder import MoETransformerConfig
    from automodel_tpu.moe.config import MoEConfig

    cfg = MoETransformerConfig(
        vocab_size=64, hidden_size=32, intermediate_size=48, num_layers=3,
        num_heads=4, num_kv_heads=4, first_k_dense=1, dtype=jnp.float32,
        remat_policy="none",
        attention_type="mla", mla_kv_lora_rank=16, mla_q_lora_rank=12,
        mla_qk_nope_head_dim=8, mla_qk_rope_head_dim=8, mla_v_head_dim=8,
        moe=MoEConfig(
            n_routed_experts=4, n_shared_experts=1, experts_per_token=2,
            moe_intermediate_size=16, shared_expert_intermediate_size=16,
            aux_loss_coeff=0.0,
            # decode forces dropless (exact for any token population); use it
            # in the oracle too so near-tie argmaxes see identical fp noise
            dispatcher="dropless",
        ),
    )
    params = moe_decoder.init(cfg, jax.random.key(0))
    prompt = jax.random.randint(jax.random.key(13), (2, 7), 0, 64)
    fast = generate(params, cfg, prompt, jax.random.key(2), GenerateConfig(max_new_tokens=5))
    ids = prompt
    for _ in range(5):
        logits, _aux = moe_decoder.forward(params, cfg, ids)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        ids = jnp.concatenate([ids, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(np.asarray(fast), np.asarray(ids))


@pytest.mark.slow
def test_sliding_window_matches_naive():
    import dataclasses

    cfg = dataclasses.replace(CFG, sliding_window=4)
    params = decoder.init(cfg, jax.random.key(0))
    prompt = jax.random.randint(jax.random.key(9), (2, 7), 0, 64)
    fast = generate(params, cfg, prompt, jax.random.key(2), GenerateConfig(max_new_tokens=6))
    slow = _naive_greedy(params, cfg, prompt, 6)
    np.testing.assert_array_equal(np.asarray(fast), np.asarray(slow))


@pytest.mark.slow
def test_alternating_windows_and_sinks_match_naive():
    """gemma2/gpt-oss shape: per-layer sliding/global pattern + sinks."""
    import dataclasses

    cfg = dataclasses.replace(
        CFG, sliding_window=4, layer_types=("sliding", "global"),
        attention_sinks=True,
    )
    params = decoder.init(cfg, jax.random.key(0))
    # non-zero sinks so the path is actually exercised
    params["layers"]["sinks"] = 0.5 + 0.1 * jax.random.normal(
        jax.random.key(11), params["layers"]["sinks"].shape
    )
    prompt = jax.random.randint(jax.random.key(10), (2, 7), 0, 64)
    fast = generate(params, cfg, prompt, jax.random.key(2), GenerateConfig(max_new_tokens=6))
    slow = _naive_greedy(params, cfg, prompt, 6)
    np.testing.assert_array_equal(np.asarray(fast), np.asarray(slow))


@pytest.mark.slow
def test_eos_early_stop_pads_with_eos():
    """After EOS is sampled, all subsequent tokens are EOS."""
    params = decoder.init(CFG, jax.random.key(0))
    prompt = jax.random.randint(jax.random.key(8), (1, 5), 0, 64)
    # find what greedy emits, then declare that token the "EOS"
    probe = generate(params, CFG, prompt, jax.random.key(0), GenerateConfig(max_new_tokens=4))
    eos = int(probe[0, 5 + 1])  # second generated token
    out = generate(
        params, CFG, prompt, jax.random.key(0),
        GenerateConfig(max_new_tokens=8, eos_token_id=eos),
    )
    gen_tokens = np.asarray(out[0, 5:])
    hits = np.flatnonzero(gen_tokens == eos)
    assert len(hits) > 0
    first = hits[0]
    assert (gen_tokens[first:] == eos).all()


@pytest.mark.slow
def test_top_k_top_p_sampling():
    """top-k restricts samples to the k best tokens; top-p to the nucleus."""
    from automodel_tpu.inference.generate import _filter_logits

    logits = jnp.log(jnp.asarray([[0.5, 0.3, 0.1, 0.06, 0.04]]))
    f = np.asarray(_filter_logits(logits, GenerateConfig(top_k=2)))
    assert np.isfinite(f[0, :2]).all() and (f[0, 2:] < -1e30).all()
    # top_p=0.75: cumulative 0.5, 0.8 — the crossing token (0.3) is kept
    f = np.asarray(_filter_logits(logits, GenerateConfig(top_p=0.75)))
    assert np.isfinite(f[0, :2]).all() and (f[0, 2:] < -1e30).all()

    # end to end: every sampled token comes from the top-k set
    params = decoder.init(CFG, jax.random.key(0))
    prompt = jax.random.randint(jax.random.key(5), (1, 4), 0, 64)
    out = generate(
        params, CFG, prompt, jax.random.key(6),
        GenerateConfig(max_new_tokens=8, temperature=1.0, top_k=1),
    )
    greedy = generate(
        params, CFG, prompt, jax.random.key(7),
        GenerateConfig(max_new_tokens=8),
    )
    # top_k=1 sampling == greedy regardless of temperature/key
    np.testing.assert_array_equal(np.asarray(out), np.asarray(greedy))


def test_sampling_degenerate_params():
    """top_k=0 / top_p>=1 mean off; top_p<=0 keeps exactly the best token."""
    from automodel_tpu.inference.generate import _filter_logits

    logits = jnp.log(jnp.asarray([[0.5, 0.3, 0.1, 0.06, 0.04]]))
    off1 = np.asarray(_filter_logits(logits, GenerateConfig(top_k=0)))
    off2 = np.asarray(_filter_logits(logits, GenerateConfig(top_p=1.0)))
    np.testing.assert_array_equal(off1, np.asarray(logits))
    np.testing.assert_array_equal(off2, np.asarray(logits))
    only_best = np.asarray(_filter_logits(logits, GenerateConfig(top_p=0.0)))
    assert np.isfinite(only_best[0, 0]) and (only_best[0, 1:] < -1e30).all()
