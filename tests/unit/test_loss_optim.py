import jax
import jax.numpy as jnp
import numpy as np
import pytest

from automodel_tpu.loss import (
    IGNORE_INDEX,
    cross_entropy_sum,
    fused_linear_cross_entropy,
    masked_cross_entropy,
)
from automodel_tpu.optim import LRSchedulerConfig, OptimizerConfig


def test_masked_ce_matches_naive():
    rng = np.random.default_rng(0)
    logits = jnp.asarray(rng.normal(size=(2, 8, 32)), jnp.float32)
    labels = jnp.asarray(rng.integers(0, 32, size=(2, 8)))
    labels = labels.at[0, :4].set(IGNORE_INDEX)
    ce_sum, n = cross_entropy_sum(logits, labels)
    assert n == 12
    # naive reference
    logp = jax.nn.log_softmax(logits, axis=-1)
    total = 0.0
    for b in range(2):
        for s in range(8):
            if labels[b, s] != IGNORE_INDEX:
                total -= logp[b, s, labels[b, s]]
    np.testing.assert_allclose(float(ce_sum), float(total), rtol=1e-5)
    mean = masked_cross_entropy(logits, labels, reduction="mean")
    np.testing.assert_allclose(float(mean), float(total) / 12, rtol=1e-5)


@pytest.mark.parametrize("chunk", [3, 8, 64])
def test_fused_linear_ce_matches_unfused(chunk):
    rng = np.random.default_rng(1)
    B, S, H, V = 2, 10, 16, 40
    hidden = jnp.asarray(rng.normal(size=(B, S, H)), jnp.float32)
    kernel = jnp.asarray(rng.normal(size=(H, V)), jnp.float32)
    labels = jnp.asarray(rng.integers(0, V, size=(B, S)))
    labels = labels.at[1, 5:].set(IGNORE_INDEX)

    logits = hidden @ kernel
    ref_sum, ref_n = cross_entropy_sum(logits, labels)
    got_sum, got_n = fused_linear_cross_entropy(hidden, kernel, labels, chunk_size=chunk)
    assert got_n == ref_n
    np.testing.assert_allclose(float(got_sum), float(ref_sum), rtol=1e-4)


def test_fused_linear_ce_grad_matches():
    rng = np.random.default_rng(2)
    B, S, H, V = 1, 8, 8, 16
    hidden = jnp.asarray(rng.normal(size=(B, S, H)), jnp.float32)
    kernel = jnp.asarray(rng.normal(size=(H, V)), jnp.float32)
    labels = jnp.asarray(rng.integers(0, V, size=(B, S)))

    def fused(h, w):
        s, n = fused_linear_cross_entropy(h, w, labels, chunk_size=4)
        return s / n

    def unfused(h, w):
        s, n = cross_entropy_sum(h @ w, labels)
        return s / n

    g1h, g1w = jax.grad(fused, argnums=(0, 1))(hidden, kernel)
    g2h, g2w = jax.grad(unfused, argnums=(0, 1))(hidden, kernel)
    np.testing.assert_allclose(np.asarray(g1h), np.asarray(g2h), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(g1w), np.asarray(g2w), rtol=1e-4, atol=1e-5)


def test_lr_schedules():
    sched = LRSchedulerConfig(warmup_steps=10, decay_steps=90, style="cosine", min_lr_ratio=0.1).build(1.0)
    assert float(sched(0)) == 0.0
    np.testing.assert_allclose(float(sched(10)), 1.0, rtol=1e-6)
    assert 0.099 < float(sched(100)) < 0.101
    wsd = LRSchedulerConfig(warmup_steps=5, stable_steps=50, decay_steps=45, style="wsd").build(2.0)
    np.testing.assert_allclose(float(wsd(30)), 2.0, rtol=1e-6)
    assert float(wsd(100)) < 0.01


def test_optimizer_no_decay_on_norms():
    params = {"w": jnp.ones((4, 4)), "norm": {"scale": jnp.ones((4,))}}
    tx = OptimizerConfig(name="adamw", lr=0.0, weight_decay=1.0).build()
    state = tx.init(params)
    grads = jax.tree.map(jnp.zeros_like, params)
    updates, _ = tx.update(grads, state, params)
    # lr=0 → no update at all; now with lr>0, decay should hit w but not scale
    tx2 = OptimizerConfig(name="adamw", lr=0.1, weight_decay=1.0).build()
    st2 = tx2.init(params)
    up2, _ = tx2.update(grads, st2, params)
    assert float(jnp.abs(up2["w"]).sum()) > 0
    assert float(jnp.abs(up2["norm"]["scale"]).sum()) == 0


def test_lora_merge_math():
    import jax
    import jax.numpy as jnp
    from automodel_tpu.peft.lora import LoRAConfig, init_lora, merge_lora

    base = {"layers": {"q_proj": {"kernel": jnp.ones((2, 8, 4))},
                       "down_proj": {"kernel": jnp.ones((2, 4, 8))}}}
    cfg = LoRAConfig(r=2, alpha=4.0, target_modules=("q_proj",))
    lora = init_lora(base, cfg, jax.random.key(0))
    assert list(lora) == ["layers/q_proj/kernel"]
    # b starts zero → merged == base
    merged = merge_lora(base, lora, cfg)
    np.testing.assert_array_equal(
        np.asarray(merged["layers"]["q_proj"]["kernel"]), 1.0
    )
    # nonzero b → delta = scale * a@b
    lora["layers/q_proj/kernel"]["b"] = jnp.ones((2, 2, 4))
    merged = merge_lora(base, lora, cfg)
    a = lora["layers/q_proj/kernel"]["a"]
    expect = 1.0 + 2.0 * np.asarray(a).sum(-1, keepdims=True).repeat(4, -1)
    np.testing.assert_allclose(
        np.asarray(merged["layers"]["q_proj"]["kernel"]), expect, rtol=1e-5
    )
    np.testing.assert_array_equal(
        np.asarray(merged["layers"]["down_proj"]["kernel"]), 1.0
    )
