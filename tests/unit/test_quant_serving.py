"""Quantized serving: int8 KV pages with per-page scales + int8 linears.

The acceptance contract of the quantized tier (docs/SERVING.md
§"Quantized serving"):

- scale rows travel with their pages through every page-movement op (the
  in-step COW copy, defrag compaction, the disagg handoff transfer)
  because they are pool leaves indexed by the same global page IDs — the
  host-side allocator / scheduler / prefix cache never learn the pool is
  quantized;
- the quantized engine is SELF-consistent exactly: prefix-cache COW,
  lossless greedy speculation, preemption churn, the disaggregated
  handoff, and tp2 sharding all reproduce the plain quant engine's
  greedy stream token for token (the identical quantized arithmetic runs
  in every path — a dequant-requant round trip anywhere would break it);
- vs the fp engine the contract is TOLERANCE, not bit-equality: on a
  model with confident predictions greedy top-1 agreement >= 0.99 (an
  untrained random init has top-1 margins below any quantization noise
  floor, so agreement there measures coin flips, not correctness);
- ONE compiled step signature (fixed-shape contract survives the extra
  pool leaves), and the engine-lifetime allocator identity
  `num_free + cached_pages == num_pages` after preempt/churn storms.

The quantized step's compiled structure (collective-free, donation over
all four pool leaves, the int8-payload + scale-row gather floor, zero
bf16→f32 upcasts) is pinned separately by the `quant_serve_step` /
`quant_kv_transfer` analysis baselines (test_hlo_guards).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from automodel_tpu.distributed import MeshConfig
from automodel_tpu.models.llm import decoder
from automodel_tpu.models.llm.decoder import TransformerConfig
from automodel_tpu.serving import (
    DisaggConfig,
    DisaggRouter,
    KVTransfer,
    PrefixCacheConfig,
    Request,
    ServingConfig,
    ServingEngine,
    SpeculativeConfig,
)
from automodel_tpu.serving.kv_pages import (
    apply_defrag,
    init_pool,
    pool_bytes,
)
from automodel_tpu.serving.kv_transfer import apply_transfer

CFG = TransformerConfig(
    vocab_size=64, hidden_size=32, intermediate_size=48, num_layers=2,
    num_heads=4, num_kv_heads=2, qk_norm=True, dtype=jnp.float32,
    remat_policy="none",
)
MLA = dataclasses.replace(
    CFG, qk_norm=False, attention_type="mla", mla_kv_lora_rank=16,
    mla_q_lora_rank=12, mla_qk_nope_head_dim=8, mla_qk_rope_head_dim=8,
    mla_v_head_dim=8,
)
QUANT = dict(kv_cache_dtype="int8", serve_precision="int8")


@pytest.fixture(scope="module")
def params():
    return decoder.init(CFG, jax.random.key(0))


def _prompts(lens, seed0=0):
    return [
        [int(t) for t in np.random.default_rng(seed0 + i).integers(1, 64, (l,))]
        for i, l in enumerate(lens)
    ]


def _reqs(prompts, arrivals, max_new=6):
    return [
        Request(prompt=list(p), max_new_tokens=max_new, arrival=a)
        for p, a in zip(prompts, arrivals)
    ]


def _serve(params, cfg, sc, requests, mesh_ctx=None):
    eng = ServingEngine(params, cfg, sc, mesh_ctx=mesh_ctx)
    res = eng.serve_batch(requests)
    assert res["stats"]["compiled_signatures"] == 1, res["stats"]
    return eng, res


# -- pool plumbing -----------------------------------------------------------
def test_init_quant_pool_shapes_and_dtypes():
    """Quantized stacks are 4-leaf: int8 payloads at the fp shapes plus
    (L, N+1, ps) f32 scale planes initialized to identity dequant."""
    (gqa,) = init_pool(CFG, [CFG.num_layers], 8, 4, kv_cache_dtype="int8")
    k, v, ks, vs = gqa
    D = CFG.resolved_head_dim
    assert k.shape == v.shape == (2, 9, 4, CFG.num_kv_heads, D)
    assert k.dtype == v.dtype == jnp.int8
    assert ks.shape == vs.shape == (2, 9, 4)
    assert ks.dtype == vs.dtype == jnp.float32
    assert bool(jnp.all(ks == 1.0)) and bool(jnp.all(vs == 1.0))

    (mla,) = init_pool(MLA, [MLA.num_layers], 8, 4, kv_cache_dtype="int8")
    c, kr, cs, krs = mla
    assert c.shape == (2, 9, 4, MLA.mla_kv_lora_rank)
    assert kr.shape == (2, 9, 4, MLA.mla_qk_rope_head_dim)
    assert c.dtype == kr.dtype == jnp.int8
    assert cs.shape == krs.shape == (2, 9, 4)

    # int8 + f32-scale pool is well under half the f32 pool (>= 1.8x even
    # against a bf16 pool: 2 bytes -> 1 + 4/ps)
    (fp,) = init_pool(CFG, [CFG.num_layers], 8, 4)
    assert pool_bytes([fp]) / pool_bytes([gqa]) > 3.0


def test_defrag_moves_scales_with_pages():
    """apply_defrag gathers along the page axis of EVERY leaf — a moved
    page's scale rows arrive at the new page ID with its int8 payload."""
    (stack,) = init_pool(CFG, [CFG.num_layers], 4, 2, kv_cache_dtype="int8")
    k, v, ks, vs = stack
    k = k.at[:, 3].set(7)
    ks = ks.at[:, 3].set(0.25)
    # plan: live page 3 compacts to slot 0; rest backfilled from free pages
    src = jnp.asarray([3, 1, 2, 0], jnp.int32)
    (k2, v2, ks2, vs2) = apply_defrag([(k, v, ks, vs)], src)[0]
    assert bool(jnp.all(k2[:, 0] == 7))
    assert bool(jnp.all(ks2[:, 0] == 0.25))
    # trash page stayed put, identity scales everywhere else
    assert bool(jnp.all(ks2[:, 1:] == 1.0))


def test_transfer_ships_scale_planes_natively():
    """apply_transfer copies int8 payload AND scale rows page-for-page —
    the handoff never dequantizes, so adopted pages are bit-identical."""
    src = init_pool(CFG, [CFG.num_layers], 4, 2, kv_cache_dtype="int8")
    dst = init_pool(CFG, [CFG.num_layers], 4, 2, kv_cache_dtype="int8")
    k, v, ks, vs = src[0]
    src[0] = (k.at[:, 1].set(-5), v, ks.at[:, 1].set(0.5), vs)
    out = apply_transfer(dst, src, jnp.asarray([1], jnp.int32),
                         jnp.asarray([2], jnp.int32))
    k2, _, ks2, _ = out[0]
    assert bool(jnp.all(k2[:, 2] == -5))
    assert bool(jnp.all(ks2[:, 2] == 0.5))


def test_step_cow_copies_scale_rows(params):
    """The in-step COW block is a pytree copy along the page axis: the
    destination page's scale rows equal the source's after the step."""
    eng = ServingEngine(params, CFG, ServingConfig(
        page_size=4, num_pages=16, max_slots=2, pages_per_slot=4,
        token_budget=8, **QUANT,
    ))
    k, v, ks, vs = eng.pool[0]
    eng.pool[0] = (k.at[:, 2].set(9), v, ks.at[:, 2].set(0.125), vs)
    T, S, P, trash = 8, 2, 4, 16
    batch = {key: jnp.full(T, trash if key == "page" else 0, jnp.int32)
             for key in ("tok", "slot", "pos", "page", "off")}
    batch.update(
        page_tables=jnp.full((S, P), trash, jnp.int32),
        sample_tok=jnp.zeros(S, jnp.int32),
        temp=jnp.zeros(S, jnp.float32),
        seed=jnp.zeros(S, jnp.int32),
        cow_src=jnp.asarray([2, trash], jnp.int32),
        cow_dst=jnp.asarray([5, trash], jnp.int32),
    )
    new_pool, _, _ = eng._step(eng.params, eng.pool, batch)
    k2, _, ks2, _ = new_pool[0]
    assert bool(jnp.all(k2[:, 5] == 9))
    assert bool(jnp.all(ks2[:, 5] == 0.125))


# -- exact self-parity across every serving feature --------------------------
def test_quant_prefix_cache_cow_parity(params):
    """Radix hits + COW against the plain quant engine: adopted pages are
    shared quantized pages (scales adopt with them), so tokens match
    exactly and hits actually fired."""
    rng = np.random.default_rng(1)
    system = [int(t) for t in rng.integers(1, 64, (8,))]
    prompts = [
        system + [int(t) for t in rng.integers(1, 64, (3,))],
        system + [int(t) for t in rng.integers(1, 64, (2,))],
    ]
    geo = dict(page_size=4, num_pages=32, max_slots=2, pages_per_slot=8,
               token_budget=8, prefill_chunk=4)
    _, base = _serve(params, CFG, ServingConfig(**geo, **QUANT),
                     _reqs(prompts, (0, 2)))
    eng, warm = _serve(
        params, CFG,
        ServingConfig(**geo, **QUANT,
                      prefix_cache=PrefixCacheConfig(enabled=True)),
        _reqs(prompts, (0, 2)),
    )
    assert warm["outputs"] == base["outputs"]
    assert warm["stats"]["prefix_hits"] >= 1, warm["stats"]
    # engine-lifetime allocator identity: free + radix-cached == total
    assert (eng.alloc.num_free + eng.prefix.cached_pages
            == eng.serve_cfg.num_pages)


def test_quant_speculation_parity(params):
    """Greedy draft-then-verify over the quantized pool is lossless: the
    verifier's argmax IS the quant engine's argmax."""
    prompts = _prompts([9, 7], seed0=40)
    geo = dict(page_size=4, num_pages=32, max_slots=2, pages_per_slot=8,
               token_budget=8, prefill_chunk=4)
    _, base = _serve(params, CFG, ServingConfig(**geo, **QUANT),
                     _reqs(prompts, (0, 0), max_new=8))
    _, spec = _serve(
        params, CFG,
        ServingConfig(**geo, **QUANT,
                      speculative=SpeculativeConfig(enabled=True, draft_len=4)),
        _reqs(prompts, (0, 0), max_new=8),
    )
    assert spec["outputs"] == base["outputs"]
    assert spec["stats"]["drafted_tokens"] >= 1, spec["stats"]


def test_quant_preemption_parity(params):
    """A tight pool forces recompute-style preemption (truncate drops the
    provisional tail — its stale scale rows are simply overwritten at the
    next quantize-at-scatter); greedy tokens match the untight engine."""
    prompts = _prompts([4, 4, 4], seed0=20)
    roomy = dict(page_size=2, num_pages=32, max_slots=3, pages_per_slot=6,
                 token_budget=6, prefill_chunk=3)
    tight = dict(roomy, num_pages=8)
    _, base = _serve(params, CFG, ServingConfig(**roomy, **QUANT),
                     _reqs(prompts, (0, 0, 0), 5))
    eng, res = _serve(
        params, CFG,
        ServingConfig(**tight, **QUANT,
                      prefix_cache=PrefixCacheConfig(enabled=True)),
        _reqs(prompts, (0, 0, 0), 5),
    )
    assert res["outputs"] == base["outputs"]
    assert res["stats"]["preemptions"] >= 1
    # after the storm every page is free or radix-cached — a scale-aware
    # leak anywhere in the churn path would break the lifetime identity
    assert eng.alloc.num_free + eng.prefix.cached_pages == 8


def test_quant_disagg_handoff_parity(params):
    """Prefill→decode handoff ships quantized pages natively: router
    tokens equal the monolithic quant engine's, and the wire-bytes
    counter advanced by pages × quantized page_bytes (~half the fp
    engine's page_bytes)."""
    sc = ServingConfig(
        page_size=4, num_pages=32, max_slots=2, pages_per_slot=8,
        token_budget=8, prefill_chunk=4, **QUANT,
    )
    prompts = _prompts([6, 9, 4], seed0=30)
    _, mono = _serve(params, CFG, sc, _reqs(prompts, (0, 1, 3)))
    router = DisaggRouter(params, CFG, sc, DisaggConfig(
        prefill_replicas=1, decode_replicas=1,
    ))
    res = router.serve_batch(_reqs(prompts, (0, 1, 3)))
    assert res["outputs"] == mono["outputs"]
    transfers = list(router.transfers.values())
    assert sum(t.n_pages for t in transfers) >= 1
    assert all(t.n_bytes == t.n_pages * t.page_bytes for t in transfers)
    # quantized wire bytes: >= 1.8x fewer than the same handoff in fp
    fp_sc = dataclasses.replace(sc, kv_cache_dtype=None, serve_precision=None)
    fp_router = DisaggRouter(params, CFG, fp_sc, DisaggConfig(
        prefill_replicas=1, decode_replicas=1,
    ))
    fp_router.serve_batch(_reqs(prompts, (0, 1, 3)))
    fp_pb = next(iter(fp_router.transfers.values())).page_bytes
    q_pb = transfers[0].page_bytes
    assert fp_pb / q_pb >= 1.8, (fp_pb, q_pb)


def test_quant_tp2_parity(params):
    """tp2 shards the int8 KV heads while the scale planes replicate;
    greedy tokens equal the single-chip quant engine's through the
    sharded gather-dequant attention."""
    sc = ServingConfig(
        page_size=2, num_pages=8, max_slots=3, pages_per_slot=6,
        token_budget=6, prefill_chunk=3, **QUANT,
    )
    prompts = _prompts([4, 4, 4], seed0=20)
    _, base = _serve(params, CFG, sc, _reqs(prompts, (0, 0, 0), 5))
    ctx = MeshConfig(tp=2, dp_shard=1).build(jax.devices()[:2])
    eng, tp2 = _serve(params, CFG, sc, _reqs(prompts, (0, 0, 0), 5),
                      mesh_ctx=ctx)
    assert tp2["outputs"] == base["outputs"]
    # int8 payload sharded over kv heads; scale planes replicated
    k, v, ks, vs = eng.pool[0]
    assert k.sharding.spec[3] == "tp"
    assert all(s is None for s in ks.sharding.spec)


def test_quant_mla_stream_compiles_once():
    """Absorbed-MLA quantized pool (int8 latent + rope stripes, separate
    scale planes) serves a ragged stream geometry-independently: tokens
    match across pool sizes, one compiled signature each."""
    params = decoder.init(MLA, jax.random.key(0))
    prompts = _prompts([6, 9, 4], seed0=10)
    small = dict(page_size=4, num_pages=20, max_slots=3, pages_per_slot=5,
                 token_budget=6, prefill_chunk=3)
    big = dict(small, num_pages=40, pages_per_slot=10)
    _, a = _serve(params, MLA, ServingConfig(**small, **QUANT),
                  _reqs(prompts, (0, 1, 2), 5))
    _, b = _serve(params, MLA, ServingConfig(**big, **QUANT),
                  _reqs(prompts, (0, 1, 2), 5))
    assert a["outputs"] == b["outputs"]


# -- tolerance vs the fp engine ----------------------------------------------
@pytest.mark.slow
def test_quant_vs_fp_greedy_agreement_confident_model():
    """The tolerance contract: a model with real top-1 margins (briefly
    trained on a deterministic next-token mapping) keeps >= 0.99 greedy
    top-1 agreement between the int8 engine and the fp engine."""
    import optax

    from automodel_tpu.loss import fused_linear_cross_entropy

    V = CFG.vocab_size
    params = decoder.init(CFG, jax.random.key(0))

    def f_next(tok):
        return (tok * 3 + 7) % (V - 1) + 1

    def loss_fn(p, ids, labels):
        h = decoder.forward(p, CFG, ids, return_hidden=True)
        ce, n = fused_linear_cross_entropy(
            h, p["lm_head"]["kernel"], labels, chunk_size=64
        )
        return ce / n

    tx = optax.adam(3e-3)

    @jax.jit
    def train_one(p, o, key):
        ids = jax.random.randint(key, (8, 32), 1, V)
        _, g = jax.value_and_grad(loss_fn)(p, ids, f_next(ids))
        up, o = tx.update(g, o, p)
        return optax.apply_updates(p, up), o

    opt = tx.init(params)
    key = jax.random.key(1)
    for _ in range(150):
        key, k = jax.random.split(key)
        params, opt = train_one(params, opt, k)

    sc = dict(page_size=4, num_pages=32, max_slots=3, pages_per_slot=8,
              token_budget=8, prefill_chunk=4)
    prompts = _prompts([5, 9, 3, 7], seed0=50)
    _, fp = _serve(params, CFG, ServingConfig(**sc),
                   _reqs(prompts, (0, 0, 2, 3), 8))
    _, qt = _serve(params, CFG, ServingConfig(**sc, **QUANT),
                   _reqs(prompts, (0, 0, 2, 3), 8))
    agree = sum(
        a == b
        for o1, o2 in zip(fp["outputs"], qt["outputs"])
        for a, b in zip(o1, o2)
    )
    total = sum(len(o) for o in fp["outputs"])
    assert agree / total >= 0.99, (agree, total, fp["outputs"], qt["outputs"])


# -- config validation -------------------------------------------------------
def test_quant_config_validation(params):
    with pytest.raises(AssertionError):
        ServingConfig(page_size=4, num_pages=8, max_slots=1,
                      pages_per_slot=2, kv_cache_dtype="int4")
    with pytest.raises(AssertionError):
        ServingConfig(page_size=4, num_pages=8, max_slots=1,
                      pages_per_slot=2, serve_precision="int2")
