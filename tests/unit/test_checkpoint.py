import dataclasses
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from automodel_tpu.checkpoint import (
    CheckpointingConfig,
    DenseDecoderAdapter,
    HFCheckpointReader,
    MoEDecoderAdapter,
    abstract_state_like,
    save_hf_checkpoint,
)
from automodel_tpu.distributed import MeshConfig
from automodel_tpu.models.llm import decoder
from automodel_tpu.models.llm.decoder import TransformerConfig
from automodel_tpu.models.moe_lm import decoder as moe_decoder
from automodel_tpu.moe.config import MoEConfig
from automodel_tpu.optim import OptimizerConfig
from automodel_tpu.parallel import logical_to_shardings
from automodel_tpu.training import init_train_state

CFG = TransformerConfig(
    vocab_size=64, hidden_size=32, intermediate_size=48, num_layers=2,
    num_heads=4, num_kv_heads=2, attention_bias=True, qk_norm=True,
    dtype=jnp.float32, remat_policy="none",
)


def _trees_equal(a, b, rtol=0):
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), rtol=rtol)


def test_dense_hf_roundtrip(tmp_path):
    params = decoder.init(CFG, jax.random.key(0))
    adapter = DenseDecoderAdapter(CFG)
    save_hf_checkpoint(adapter.to_hf(params), str(tmp_path), hf_config={"architectures": ["X"]})
    reader = HFCheckpointReader(str(tmp_path))
    assert reader.hf_config() == {"architectures": ["X"]}
    # HF naming present
    assert "model.layers.0.self_attn.q_proj.weight" in reader.keys()
    assert "model.layers.1.self_attn.q_norm.weight" in reader.keys()
    restored = adapter.from_hf(reader)
    _trees_equal(params, restored)


def test_dense_hf_roundtrip_sharded(tmp_path):
    ctx = MeshConfig(dp_shard=4, tp=2).build()
    params = decoder.init(CFG, jax.random.key(0))
    shardings = logical_to_shardings(
        decoder.param_specs(CFG), ctx, shapes=jax.tree.map(lambda p: p.shape, params)
    )
    adapter = DenseDecoderAdapter(CFG)
    save_hf_checkpoint(adapter.to_hf(params), str(tmp_path))
    restored = adapter.from_hf(HFCheckpointReader(str(tmp_path)), shardings=shardings)
    # placed directly into the sharded layout
    leaf = restored["layers"]["q_proj"]["kernel"]
    assert len(leaf.sharding.device_set) == 8
    _trees_equal(params, restored)


def test_hf_sharding_splits_files(tmp_path):
    params = decoder.init(CFG, jax.random.key(0))
    adapter = DenseDecoderAdapter(CFG)
    save_hf_checkpoint(adapter.to_hf(params), str(tmp_path), max_shard_bytes=40_000)
    files = os.listdir(tmp_path)
    assert "model.safetensors.index.json" in files
    assert sum(f.endswith(".safetensors") for f in files) > 1
    restored = adapter.from_hf(HFCheckpointReader(str(tmp_path)))
    _trees_equal(params, restored)


MOE_CFG_T = None


def _moe_cfg():
    from automodel_tpu.models.moe_lm.decoder import MoETransformerConfig

    return MoETransformerConfig(
        vocab_size=64, hidden_size=32, intermediate_size=48, num_layers=3,
        num_heads=4, num_kv_heads=2, first_k_dense=1,
        moe=MoEConfig(
            n_routed_experts=4, n_shared_experts=1, experts_per_token=2,
            moe_intermediate_size=16, shared_expert_intermediate_size=16,
            gate_bias_update_speed=0.01,
        ),
        dtype=jnp.float32, remat_policy="none",
    )


@pytest.mark.slow
def test_moe_hf_roundtrip(tmp_path):
    cfg = _moe_cfg()
    params = moe_decoder.init(cfg, jax.random.key(0))
    adapter = MoEDecoderAdapter(cfg)
    save_hf_checkpoint(adapter.to_hf(params), str(tmp_path))
    reader = HFCheckpointReader(str(tmp_path))
    assert "model.layers.1.mlp.experts.0.gate_proj.weight" in reader.keys()
    assert "model.layers.0.mlp.gate_proj.weight" in reader.keys()  # dense layer 0
    assert "model.layers.2.mlp.shared_experts.up_proj.weight" in reader.keys()
    restored = adapter.from_hf(reader)
    _trees_equal(params, restored)


def test_orbax_save_restore_roundtrip(tmp_path):
    params = decoder.init(CFG, jax.random.key(0))
    tx = OptimizerConfig(lr=1e-3).build()
    state = init_train_state(params, tx)
    ckpt = CheckpointingConfig(
        checkpoint_dir=str(tmp_path / "ckpt"), async_save=False, save_every_steps=2
    ).build()
    assert not ckpt.should_save(1)
    assert ckpt.should_save(2)
    ok = ckpt.save(2, state, extra={"epoch": 1, "data_step": 17})
    assert ok
    ckpt.wait()
    assert ckpt.latest_step() == 2

    restored, extra = ckpt.restore(abstract_state_like(state), with_extra=True)
    assert extra == {"epoch": 1, "data_step": 17}
    _trees_equal(state.params, restored.params)
    _trees_equal(state.opt_state, restored.opt_state)
    ckpt.close()


def test_orbax_restore_across_topology(tmp_path):
    """Save unsharded, restore into an FSDP+TP layout (DCP-reshard analog)."""
    params = decoder.init(CFG, jax.random.key(0))
    tx = OptimizerConfig(lr=1e-3).build()
    state = init_train_state(params, tx)
    ckpt = CheckpointingConfig(checkpoint_dir=str(tmp_path / "c"), async_save=False).build()
    ckpt.save(0, state, force=True)
    ckpt.wait()

    ctx = MeshConfig(dp_shard=4, tp=2).build()
    shardings = logical_to_shardings(
        decoder.param_specs(CFG), ctx, shapes=jax.tree.map(lambda p: p.shape, params)
    )
    sharded_params = jax.device_put(params, shardings)
    target = init_train_state(sharded_params, tx)
    restored = ckpt.restore(abstract_state_like(target))
    assert len(restored.params["layers"]["q_proj"]["kernel"].sharding.device_set) == 8
    _trees_equal(state.params, restored.params)
    ckpt.close()


def test_retention(tmp_path):
    params = {"w": jnp.zeros((4,))}
    tx = OptimizerConfig(lr=1e-3).build()
    state = init_train_state(params, tx)
    ckpt = CheckpointingConfig(
        checkpoint_dir=str(tmp_path / "r"), async_save=False, max_recent_checkpoints=2
    ).build()
    for s in (1, 2, 3, 4):
        ckpt.save(s, state, force=True)
    ckpt.wait()
    steps = sorted(int(d) for d in os.listdir(tmp_path / "r") if d.isdigit())
    assert steps == [3, 4]
    ckpt.close()


def test_is_remote_path_classification():
    from automodel_tpu.checkpoint.checkpointer import is_remote_path

    assert is_remote_path("gs://bucket/run1")
    assert is_remote_path("s3://bucket/ckpt")
    assert is_remote_path("file://node/shared/ckpt")
    assert not is_remote_path("checkpoints")
    assert not is_remote_path("/abs/local/dir")
    assert not is_remote_path("./rel/dir")
    assert not is_remote_path("C://weird-windows-ish")  # drive letter, not a scheme


def test_consolidated_hf_export_rejects_remote_uri():
    """save_hf_checkpoint writes LOCAL safetensors; a remote out_dir (e.g.
    checkpoint_dir: gs://… + save_consolidated) must fail fast instead of
    silently materializing a local './gs:/…' tree the job loses."""
    from automodel_tpu.checkpoint.hf_adapter import save_hf_checkpoint

    with pytest.raises(NotImplementedError, match="remote URI"):
        save_hf_checkpoint(iter([]), "gs://bucket/run1/hf")


def test_remote_checkpoint_dir_skips_local_fs(monkeypatch, tmp_path):
    """gs:// checkpoint_dir goes to orbax VERBATIM — no makedirs/abspath
    (multi-host TPU jobs checkpoint to a bucket, not a shared filesystem).
    The bucket I/O itself belongs to tensorstore, so the manager is mocked."""
    import orbax.checkpoint as ocp

    from automodel_tpu.checkpoint import checkpointer as ckpt_mod

    seen = {}

    class FakeManager:
        def __init__(self, root, options=None):
            seen["root"] = root

        def wait_until_finished(self):
            pass

        def close(self):
            pass

    real_makedirs = os.makedirs

    def forbidden(*a, **k):
        raise AssertionError("os.makedirs must not run for a remote URI")

    monkeypatch.setattr(ocp, "CheckpointManager", FakeManager)
    monkeypatch.setattr(ckpt_mod.os, "makedirs", forbidden)
    ckpt = CheckpointingConfig(
        checkpoint_dir="gs://bucket/run1/", async_save=False
    ).build()
    assert seen["root"] == "gs://bucket/run1"  # trailing slash normalized only
    ckpt.close()

    # local dirs keep the old behavior: created + absolutized
    monkeypatch.setattr(ckpt_mod.os, "makedirs", real_makedirs)
    local = CheckpointingConfig(checkpoint_dir=str(tmp_path / "loc")).build()
    assert os.path.isdir(tmp_path / "loc")
    assert os.path.isabs(seen["root"]) and seen["root"].endswith("loc")
    local.close()
