"""Chaos suite for the fault-tolerance layer (automodel_tpu/resilience/).

Deterministic fault injection drives the failure scenarios in tier-1 on
CPU: transient checkpoint-write faults are retried and the run completes;
retry-budget exhaustion fails loudly; an injected NaN streak triggers
rollback + data-window skip and the run converges next to the clean curve;
a diverged run without rollback fails fast instead of silently skipping
every update; crash-before-commit never leaves a restore-able partial
checkpoint or HF export.
"""

import dataclasses
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from automodel_tpu.resilience import (
    FaultCrash,
    FaultError,
    FaultInjector,
    FaultSpec,
    ResilienceError,
    RetryBudgetExhausted,
    RetryPolicy,
    RollbackManager,
    injected,
    retry_call,
    wait_with_deadline,
)


# ---------------------------------------------------------------------------
# unit: fault injector
# ---------------------------------------------------------------------------
def test_fault_injector_step_call_times_gating():
    inj = FaultInjector([
        {"point": "a", "step": 3, "times": 2},
        {"point": "b", "call": 2},
    ])
    # step-gated: fires only when the caller reports the armed step
    assert inj.check("a", step=1) is None
    assert inj.check("a", step=3) is not None
    assert inj.check("a", step=3) is not None  # times=2
    assert inj.check("a", step=3) is None      # disarmed
    # call-gated: fires from the 2nd hit, once
    assert inj.check("b") is None
    assert inj.check("b") is not None
    assert inj.check("b") is None
    assert inj.fired["a"] == 2 and inj.fired["b"] == 1


def test_fault_modes_and_context_manager():
    with injected(FaultSpec(point="p", mode="error")):
        from automodel_tpu.resilience import fault_hit

        with pytest.raises(FaultError):
            fault_hit("p")
        assert fault_hit("p") is False  # times=1, disarmed
    with injected({"point": "p", "mode": "crash"}):
        from automodel_tpu.resilience import fault_hit

        with pytest.raises(FaultCrash):
            fault_hit("p")
    # context exited → default disarmed injector, probe is a no-op
    from automodel_tpu.resilience import fault_hit

    assert fault_hit("p") is False


def test_fault_spec_rejects_unknown_mode():
    with pytest.raises(ValueError, match="mode"):
        FaultSpec(point="x", mode="explode")


def test_resilience_disabled_disarms_everything():
    """enabled:false turns the WHOLE layer off — faults included (a chaos
    YAML toggled off for a comparison run must not keep firing with no
    retry left to absorb it)."""
    from automodel_tpu.resilience import ResilienceConfig

    cfg = ResilienceConfig(
        enabled=False, snapshot_every_steps=4,
        faults=[{"point": "checkpoint_write"}],
    )
    assert not cfg.build_injector().armed
    assert cfg.retry_policy() is None
    assert cfg.build_rollback() is None


# ---------------------------------------------------------------------------
# unit: retry
# ---------------------------------------------------------------------------
def test_retry_succeeds_after_transients_and_counts_attempts():
    calls = {"n": 0}
    seen = []

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise OSError("transient")
        return "ok"

    out = retry_call(
        flaky, policy=RetryPolicy(max_attempts=3, base_delay_s=0.0),
        point="t", on_attempt=lambda p, a, e, d: seen.append((p, a)),
    )
    assert out == "ok" and calls["n"] == 3
    assert seen == [("t", 1), ("t", 2)]  # every failed attempt observed


def test_retry_budget_exhaustion_fails_loudly():
    def always():
        raise OSError("down")

    with pytest.raises(RetryBudgetExhausted, match="2 attempt"):
        retry_call(
            always, policy=RetryPolicy(max_attempts=2, base_delay_s=0.0),
            point="t",
        )


def test_retry_never_swallows_a_crash():
    def crash():
        raise FaultCrash("dead")

    with pytest.raises(FaultCrash):
        retry_call(crash, policy=RetryPolicy(max_attempts=5, base_delay_s=0.0))


def test_retry_backoff_deterministic_and_bounded():
    p = RetryPolicy(max_attempts=5, base_delay_s=0.1, max_delay_s=0.3, jitter=0.5, seed=7)
    d1 = [p.delay(a, p.rng_for("x")) for a in (1, 2, 3, 4)]
    d2 = [p.delay(a, p.rng_for("x")) for a in (1, 2, 3, 4)]
    assert d1 == d2  # deterministic replay per (seed, point)
    assert all(d <= 0.3 * 1.5 + 1e-9 for d in d1)  # capped + jitter bound
    rng = p.rng_for("x")
    delays = [p.delay(a, rng) for a in (1, 2, 3)]
    assert delays[0] >= 0.1 and delays[1] >= delays[0] / 2  # growing base


# ---------------------------------------------------------------------------
# unit: rollback manager
# ---------------------------------------------------------------------------
def _tiny_state():
    return {"w": jnp.arange(4.0), "m": jnp.ones((2, 2))}


def test_rollback_restores_snapshot_and_counts_waste():
    rb = RollbackManager(every_steps=2, max_rollbacks=2)
    state = _tiny_state()
    rb.snapshot(4, state)
    corrupted = jax.tree.map(lambda x: x * jnp.nan, state)
    assert rb.observe(7, float("nan"), nonfinite=True) == "nonfinite"
    snap_step, restored = rb.rollback(7, "nonfinite")
    assert snap_step == 4
    for a, b in zip(jax.tree.leaves(restored), jax.tree.leaves(state)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    del corrupted
    assert rb.stats.wasted_steps == 3 and rb.stats.rollbacks == 1
    assert rb.first_bad_step == 7


def test_rollback_budget_exhaustion_raises():
    rb = RollbackManager(every_steps=1, max_rollbacks=1)
    rb.snapshot(1, _tiny_state())
    rb.rollback(2, "nonfinite")
    with pytest.raises(ResilienceError, match="budget exhausted"):
        rb.rollback(3, "nonfinite")


def test_rollback_spike_detection():
    rb = RollbackManager(every_steps=1, max_rollbacks=1, loss_spike_factor=3.0)
    for s, l in enumerate((1.0, 1.1, 0.9, 1.0, 1.05), start=1):
        assert rb.observe(s, l, nonfinite=False) is None
    assert rb.observe(6, 30.0, nonfinite=False) == "loss_spike"
    assert rb.observe(6, 1.2, nonfinite=False) is None  # normal loss passes


def test_wait_with_deadline():
    import time as _time

    class Slow:
        def wait(self):
            _time.sleep(5.0)

    class Fast:
        def wait(self):
            pass

    assert wait_with_deadline(Fast(), 1.0) is True
    assert wait_with_deadline(Slow(), 0.05) is False
    # an ALREADY-EXPIRED grace window (spent inside a long step) must probe
    # and return False promptly — never block unbounded on a stuck commit
    t0 = _time.monotonic()
    assert wait_with_deadline(Slow(), 0.0) is False
    assert _time.monotonic() - t0 < 2.0
    # …but an instantly-committing save must still report True (the probe
    # has a small floor window so it cannot race the wait thread's startup)
    assert wait_with_deadline(Fast(), 0.0) is True
    assert wait_with_deadline(Fast(), None) is True  # None = no deadline


# ---------------------------------------------------------------------------
# chaos: checkpoint write/restore under faults
# ---------------------------------------------------------------------------
def _ckpt(tmp_path, **kw):
    from automodel_tpu.checkpoint import CheckpointingConfig

    return CheckpointingConfig(
        checkpoint_dir=str(tmp_path / "ckpt"), async_save=False, **kw
    ).build()


def test_checkpoint_save_retries_transient_fault(tmp_path):
    from automodel_tpu.checkpoint import abstract_state_like

    ckpt = _ckpt(tmp_path)
    attempts = []
    ckpt.set_retry(
        RetryPolicy(max_attempts=3, base_delay_s=0.0),
        on_attempt=lambda p, a, e, d: attempts.append((p, a)),
    )
    state = {"w": jnp.arange(8.0)}
    with injected({"point": "checkpoint_write", "call": 1, "times": 2}):
        assert ckpt.save(1, state, force=True)
    assert attempts == [("checkpoint_write", 1), ("checkpoint_write", 2)]
    restored = ckpt.restore(abstract_state_like(state))
    np.testing.assert_array_equal(np.asarray(restored["w"]), np.arange(8.0))
    ckpt.close()


def test_restore_file_not_found_is_never_retried(tmp_path):
    """FileNotFoundError is deterministic; with retry enabled it must still
    surface AS FileNotFoundError (auto-resume's fresh-start fallback in
    train_ft matches on the type) instead of being burned through the
    budget and re-raised as RetryBudgetExhausted."""
    ckpt = _ckpt(tmp_path)
    attempts = []
    ckpt.set_retry(
        RetryPolicy(max_attempts=3, base_delay_s=0.0),
        on_attempt=lambda p, a, e, d: attempts.append(a),
    )
    with pytest.raises(FileNotFoundError):
        ckpt.restore({"w": jnp.zeros(2)})  # empty dir: no checkpoint at all

    def damaged_restore(*a, **k):
        raise FileNotFoundError("damaged step dir")

    ckpt._mgr.restore = damaged_restore
    with pytest.raises(FileNotFoundError, match="damaged"):
        ckpt.restore({"w": jnp.zeros(2)}, step=7)
    assert attempts == []  # zero retried attempts for either path
    ckpt.close()


def test_checkpoint_save_exhaustion_fails_loudly(tmp_path):
    ckpt = _ckpt(tmp_path)
    ckpt.set_retry(RetryPolicy(max_attempts=2, base_delay_s=0.0))
    with injected({"point": "checkpoint_write", "call": 1, "times": 5}):
        with pytest.raises(RetryBudgetExhausted, match="checkpoint_write"):
            ckpt.save(1, {"w": jnp.zeros(2)}, force=True)
    ckpt.close()


def test_crash_before_commit_leaves_no_partial_checkpoint(tmp_path):
    """A crash at the write point must never surface a partial step to
    latest_step()/restore — resume falls back to the last COMPLETE step."""
    from automodel_tpu.checkpoint import abstract_state_like

    ckpt = _ckpt(tmp_path)
    state = {"w": jnp.arange(4.0)}
    assert ckpt.save(1, state, force=True)
    ckpt.wait()
    with injected({"point": "checkpoint_write", "mode": "crash"}):
        with pytest.raises(FaultCrash):
            ckpt.save(2, {"w": jnp.full((4,), 9.0)}, force=True)
    ckpt.close()
    # a fresh process (new manager) sees only the complete step
    ckpt2 = _ckpt(tmp_path)
    assert ckpt2.latest_step() == 1
    restored = ckpt2.restore(abstract_state_like(state))
    np.testing.assert_array_equal(np.asarray(restored["w"]), np.arange(4.0))
    ckpt2.close()


# ---------------------------------------------------------------------------
# chaos: HF export crash consistency + remote-IO retry
# ---------------------------------------------------------------------------
def _dense_params_and_adapter():
    from automodel_tpu.checkpoint import DenseDecoderAdapter
    from automodel_tpu.models.llm import decoder
    from automodel_tpu.models.llm.decoder import TransformerConfig

    cfg = TransformerConfig(
        vocab_size=64, hidden_size=16, intermediate_size=32, num_layers=2,
        num_heads=2, num_kv_heads=2, dtype=jnp.float32, remat_policy="none",
    )
    return decoder.init(cfg, jax.random.key(0)), DenseDecoderAdapter(cfg)


def test_hf_export_crash_before_commit_never_truncates(tmp_path):
    from automodel_tpu.checkpoint import HFCheckpointReader, save_hf_checkpoint

    params, adapter = _dense_params_and_adapter()
    out = tmp_path / "hf"
    # crash on a FRESH export: the target directory must not exist at all
    # (a truncated safetensors set parses as a complete smaller model)
    with injected({"point": "hf_export_commit", "mode": "crash"}):
        with pytest.raises(FaultCrash):
            save_hf_checkpoint(adapter.to_hf(params), str(out), hf_config={"a": 1})
    assert not out.exists()

    # successful export, then crash while REPLACING it: old export intact
    save_hf_checkpoint(adapter.to_hf(params), str(out), hf_config={"a": 1})
    before = sorted(os.listdir(out))
    with injected({"point": "hf_export_commit", "mode": "crash"}):
        with pytest.raises(FaultCrash):
            save_hf_checkpoint(adapter.to_hf(params), str(out), hf_config={"a": 2})
    assert sorted(os.listdir(out)) == before
    reader = HFCheckpointReader(str(out))
    restored = adapter.from_hf(reader)
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(restored)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))


def test_hf_export_swap_crash_recovery_and_sidecar_preservation(tmp_path):
    """Crash BETWEEN the two publish renames: out_dir is momentarily gone
    but the old complete export sits under `.old` and the next export
    self-heals (restores, then replaces). Sidecar files a user staged next
    to the export (tokenizer.json) survive a replace; stale model shards
    never do."""
    from automodel_tpu.checkpoint import HFCheckpointReader, save_hf_checkpoint

    params, adapter = _dense_params_and_adapter()
    out = tmp_path / "hf"
    save_hf_checkpoint(adapter.to_hf(params), str(out), hf_config={"v": 1})
    (out / "tokenizer.json").write_text('{"tok": true}')

    with injected({"point": "hf_export_swap", "mode": "crash"}):
        with pytest.raises(FaultCrash):
            save_hf_checkpoint(adapter.to_hf(params), str(out), hf_config={"v": 2})
    assert not out.exists() and (tmp_path / "hf.old").is_dir()

    # next export recovers the stranded state and publishes cleanly
    save_hf_checkpoint(adapter.to_hf(params), str(out), hf_config={"v": 3})
    assert not (tmp_path / "hf.old").exists()
    assert not list(tmp_path.glob("hf.staging-*"))
    assert json.load(open(out / "config.json")) == {"v": 3}
    assert (out / "tokenizer.json").read_text() == '{"tok": true}'
    restored = adapter.from_hf(HFCheckpointReader(str(out)))
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(restored)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))


def test_hf_export_transient_write_fault_retried(tmp_path):
    from automodel_tpu.checkpoint import HFCheckpointReader, save_hf_checkpoint

    params, adapter = _dense_params_and_adapter()
    out = tmp_path / "hf"
    attempts = []
    with injected({"point": "hf_export_write", "call": 1, "times": 1}):
        save_hf_checkpoint(
            adapter.to_hf(params), str(out),
            retry_policy=RetryPolicy(max_attempts=3, base_delay_s=0.0),
            on_retry=lambda p, a, e, d: attempts.append(a),
        )
    assert attempts == [1]
    restored = adapter.from_hf(HFCheckpointReader(str(out)))
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(restored)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))


def test_reader_remote_io_retry_and_exhaustion(tmp_path):
    from automodel_tpu.checkpoint import HFCheckpointReader, save_hf_checkpoint

    params, adapter = _dense_params_and_adapter()
    save_hf_checkpoint(adapter.to_hf(params), str(tmp_path / "hf"))
    reader = HFCheckpointReader(
        str(tmp_path / "hf"),
        retry_policy=RetryPolicy(max_attempts=3, base_delay_s=0.0),
    )
    with injected({"point": "remote_io", "call": 1, "times": 2}):
        t = reader("model.embed_tokens.weight")  # two faults, three attempts
    assert t.shape == (64, 16)
    with injected({"point": "remote_io", "call": 1, "times": 10}):
        with pytest.raises(RetryBudgetExhausted, match="remote_io"):
            reader("model.norm.weight")


# ---------------------------------------------------------------------------
# chaos: end-to-end trainer recovery (recipe tier)
# ---------------------------------------------------------------------------
pytest_recipe = pytest.mark.recipe


def _smoke_cfg(tmp_path, **over):
    from automodel_tpu.config import ConfigNode

    cfg = {
        "seed": 7,
        "run_dir": str(tmp_path),
        "auto_resume": True,
        "model": {
            "hf_config": {
                "architectures": ["LlamaForCausalLM"],
                "vocab_size": 128, "hidden_size": 32, "intermediate_size": 64,
                "num_hidden_layers": 2, "num_attention_heads": 4,
                "num_key_value_heads": 2,
            },
            "dtype": "float32",
            "remat_policy": "none",
        },
        "distributed": {"dp_shard": -1},
        "dataset": {
            "_target_": "automodel_tpu.datasets.mock.MockDatasetConfig",
            "num_samples": 256, "seq_len": 32, "vocab_size": 128,
        },
        "dataloader": {"microbatch_size": 8, "grad_acc_steps": 1},
        "optimizer": {"name": "adamw", "lr": 1e-3, "weight_decay": 0.0},
        "lr_scheduler": {"warmup_steps": 1, "decay_steps": 16, "style": "cosine"},
        "step_scheduler": {"max_steps": 10, "ckpt_every_steps": 5, "num_epochs": 2},
        "checkpoint": {
            "enabled": True,
            "checkpoint_dir": str(tmp_path / "ckpt"),
            "async_save": False,
        },
        "loss": {"chunk_size": 32},
    }
    node = ConfigNode(cfg)
    for k, v in over.items():
        node.set(k, v)
    return node


def _run(cfg):
    from automodel_tpu.cli.app import resolve_recipe_class

    recipe = resolve_recipe_class(cfg)(cfg)
    recipe.setup()
    recipe.run_train_validation_loop()
    recs = [
        json.loads(l)
        for l in open(os.path.join(cfg.get("run_dir"), "training.jsonl"))
        if l.strip()
    ]
    return recipe, recs


@pytest_recipe
def test_nan_streak_rolls_back_and_converges(tmp_path):
    """Injected NaN params at step 6: the detector rolls back to the step-4
    snapshot, the offending window is skipped, and the run converges into
    the clean curve's final-loss window — vs today's alternative of either
    dying or silently skipping steps 6..10."""
    _, clean = _run(_smoke_cfg(tmp_path / "clean", **{"step_scheduler.max_steps": 8}))
    recipe, recs = _run(_smoke_cfg(
        tmp_path / "chaos",
        **{
            "step_scheduler.max_steps": 8,
            "skip_nonfinite_updates": True,
            "resilience": {
                "snapshot_every_steps": 2,
                "max_rollbacks": 2,
                "faults": [{"point": "nan_grads", "step": 6}],
            },
        },
    ))
    events = [r for r in recs if r.get("event") == "rollback"]
    assert len(events) == 1 and events[0]["reason"] == "nonfinite"
    assert events[0]["step"] == 6 and events[0]["restored_step"] in (4, 6 - 2)
    assert recipe.rollback.stats.rollbacks == 1
    assert recipe.rollback.stats.wasted_steps >= 1
    steps = [r for r in recs if "loss" in r]
    assert steps[-1]["step"] == 8
    # every post-recovery loss is finite and the run lands in the clean
    # curve's final-loss window (one batch was skipped → not identical)
    post = [r["loss"] for r in steps if r["step"] > 6]
    assert post and all(np.isfinite(l) for l in post)
    clean_final = [r["loss"] for r in clean if "loss" in r][-1]
    assert abs(steps[-1]["loss"] - clean_final) < 0.25 * abs(clean_final) + 0.1
    # goodput counters rode the records
    assert steps[-1]["rollbacks"] == 1 and steps[-1]["wasted_steps"] >= 1


@pytest_recipe
def test_diverged_run_fails_fast_without_rollback(tmp_path):
    """The satellite bugfix: skip_nonfinite_updates alone used to skip every
    step of a diverged run to completion; now the streak cap fails loudly,
    naming the first bad step."""
    cfg = _smoke_cfg(
        tmp_path,
        **{
            "skip_nonfinite_updates": True,
            "resilience": {
                "max_consecutive_nonfinite": 3,
                # persistent poison: every step from 3 on is non-finite
                "faults": [{"point": "nan_grads", "step": 3}],
            },
        },
    )
    from automodel_tpu.cli.app import resolve_recipe_class

    recipe = resolve_recipe_class(cfg)(cfg)
    recipe.setup()
    with pytest.raises(ResilienceError, match="first bad step: 3"):
        recipe.run_train_validation_loop()


@pytest_recipe
def test_recipe_checkpoint_write_fault_retried_and_counted(tmp_path):
    """A transient checkpoint-write fault mid-run is absorbed by the retry
    layer; the attempt count flows through MetricLogger into the JSONL."""
    recipe, recs = _run(_smoke_cfg(
        tmp_path,
        **{
            "step_scheduler.max_steps": 6,
            "step_scheduler.ckpt_every_steps": 3,
            "resilience": {
                "retry_attempts": 3,
                "retry_base_delay_s": 0.0,
                "faults": [{"point": "checkpoint_write", "call": 1, "times": 2}],
            },
        },
    ))
    steps = [r for r in recs if "loss" in r]
    assert steps[-1]["step"] == 6
    assert max(r.get("retry_checkpoint_write", 0) for r in recs) == 2
    assert sorted(
        int(d) for d in os.listdir(recipe.cfg.get("checkpoint.checkpoint_dir"))
        if d.isdigit()
    ) == [3, 6]


@pytest_recipe
@pytest.mark.slow  # the subprocess kill-and-resume test (test_kill_resume.py,
# tier-1) pins the REAL-signal version of this path end-to-end; this variant
# adds the flag-injected simulation for debugging without processes
def test_recipe_sigterm_fault_emergency_checkpoint_and_resume(tmp_path):
    """Injected SIGTERM at step 3 → emergency checkpoint (grace-deadline
    wait) → a fresh recipe auto-resumes and reports time_to_resume_s."""
    cfg = _smoke_cfg(
        tmp_path,
        **{
            "checkpoint.async_save": True,
            "resilience": {"faults": [{"point": "sigterm", "step": 3}]},
        },
    )
    _, recs = _run(cfg)
    steps = [r["step"] for r in recs if "loss" in r]
    assert steps[-1] == 3
    ev = [r for r in recs if r.get("event") == "emergency_checkpoint"]
    assert ev and ev[0]["committed"] and ev[0]["step"] == 3

    cfg2 = _smoke_cfg(tmp_path, **{"checkpoint.async_save": True})
    recipe2, recs2 = _run(cfg2)
    steps2 = [r for r in recs2 if "loss" in r]
    assert steps2[-1]["step"] == 10
    resumed_first = next(r for r in steps2 if r["step"] == 4)
    assert resumed_first["time_to_resume_s"] > 0
