"""Pipeline-parallel tests on the virtual 8-device mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from automodel_tpu.distributed import MeshConfig
from automodel_tpu.loss import cross_entropy_sum
from automodel_tpu.models.llm import decoder
from automodel_tpu.models.llm.decoder import TransformerConfig
from automodel_tpu.parallel import logical_to_shardings

CFG = TransformerConfig(
    vocab_size=64,
    hidden_size=32,
    intermediate_size=48,
    num_layers=4,
    num_heads=4,
    num_kv_heads=2,
    dtype=jnp.float32,
    remat_policy="none",
    pipeline_microbatches=4,
)


def _setup(pp, dp):
    ctx = MeshConfig(pp=pp, dp_shard=dp).build(jax.devices()[: pp * dp])
    params = decoder.init(CFG, jax.random.key(0))
    sh = logical_to_shardings(
        decoder.param_specs(CFG), ctx, shapes=jax.tree.map(lambda p: p.shape, params)
    )
    return ctx, params, jax.device_put(params, sh)


@pytest.mark.slow
@pytest.mark.parametrize("pp,dp", [(2, 1), (4, 1), (2, 4)])
def test_pp_forward_matches_single_device(pp, dp):
    ctx, params, sharded = _setup(pp, dp)
    B = max(4, 4 * dp)
    ids = jax.random.randint(jax.random.key(1), (B, 16), 0, 64)
    ref = decoder.forward(params, CFG, ids)

    @jax.jit
    def f(p, i):
        return decoder.forward(p, CFG, i, mesh_ctx=ctx)

    out = f(sharded, jax.device_put(ids, ctx.sharding("batch", None)))
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out), rtol=2e-4, atol=2e-4)


def test_pp_layer_stack_is_stage_sharded():
    ctx, _, sharded = _setup(4, 2)
    k = sharded["layers"]["q_proj"]["kernel"]
    assert k.sharding.spec[0] == "pp"
    # each stage holds 1/4 of the layers
    assert k.addressable_shards[0].data.shape[0] == 1


@pytest.mark.slow
def test_pp_backward_matches_single_device():
    ctx, params, sharded = _setup(2, 2)
    ids = jax.random.randint(jax.random.key(2), (8, 17), 0, 64)
    inputs, labels = ids[:, :-1], ids[:, 1:]

    def loss(p, mesh):
        logits = decoder.forward(p, CFG, inputs, mesh_ctx=mesh)
        s, n = cross_entropy_sum(logits, labels)
        return s / n

    g_ref = jax.grad(lambda p: loss(p, None))(params)
    g_pp = jax.jit(jax.grad(lambda p: loss(p, ctx)))(sharded)
    for a, b in zip(jax.tree.leaves(g_ref), jax.tree.leaves(g_pp)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-3, atol=2e-4)


@pytest.mark.slow
@pytest.mark.parametrize(
    "sizes",
    [
        {"pp": 2, "tp": 2, "dp_shard": 2},
        {"pp": 2, "cp": 2, "dp_shard": 2},
        {"pp": 2, "tp": 2, "cp": 2, "dp_shard": 1},
    ],
    ids=["pp2xtp2", "pp2xcp2", "pp2xtp2xcp2"],
)
def test_pp_composes_with_tp_cp(sizes):
    """pp×tp (explicit psum of o/down partials) and pp×cp (in-shard ring
    attention) forward + grad parity vs the single-device oracle."""
    ctx = MeshConfig(**sizes).build()
    params = decoder.init(CFG, jax.random.key(0))
    sh = logical_to_shardings(
        decoder.param_specs(CFG), ctx, shapes=jax.tree.map(lambda p: p.shape, params)
    )
    sharded = jax.device_put(params, sh)
    ids = jax.random.randint(jax.random.key(1), (8, 16), 0, 64)
    ref = decoder.forward(params, CFG, ids)

    ids_in = jax.device_put(ids, ctx.sharding("batch", "cp"))
    out = jax.jit(lambda p, i: decoder.forward(p, CFG, i, mesh_ctx=ctx))(
        sharded, ids_in
    )
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out), rtol=2e-4, atol=2e-4)

    def loss(p, mesh, i):
        h = decoder.forward(p, CFG, i, mesh_ctx=mesh, return_hidden=True)
        return jnp.mean(h**2)

    g_ref = jax.grad(lambda p: loss(p, None, ids))(params)
    g_pp = jax.jit(jax.grad(lambda p: loss(p, ctx, ids_in)))(sharded)
    for a, b in zip(jax.tree.leaves(g_ref), jax.tree.leaves(g_pp)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-3, atol=1e-4)


def test_pp_tp_rejects_indivisible_heads():
    ctx = MeshConfig(pp=2, tp=4, dp_shard=1).build()  # kv_heads=2 % 4 != 0
    params = decoder.init(CFG, jax.random.key(0))
    with pytest.raises(ValueError, match="divisible by tp"):
        decoder.forward(params, CFG, jnp.zeros((4, 16), jnp.int32), mesh_ctx=ctx)


# ---------------------------------------------------------------------------
# 1F1B
# ---------------------------------------------------------------------------
def test_1f1b_schedule_tables():
    """Schedule validity: every microbatch fwd+bwd exactly once per stage in
    order, dependencies ≥1 tick apart, ≤ P-p in flight, ideal span."""
    from automodel_tpu.parallel.pp import one_f_one_b_tables

    for M, P in [(4, 2), (8, 2), (4, 4), (6, 4), (3, 2), (8, 8)]:
        f, b = one_f_one_b_tables(M, P)
        assert f.shape[0] == 2 * (M + P - 1), (M, P, f.shape)
        fdone = np.full((P, M), 10**9)
        bdone = np.full((P, M), 10**9)
        for t in range(f.shape[0]):
            for p in range(P):
                if f[t, p] >= 0:
                    if p > 0:
                        assert fdone[p - 1, f[t, p]] < t
                    fdone[p, f[t, p]] = t
                if b[t, p] >= 0:
                    assert fdone[p, b[t, p]] < t or (
                        p == P - 1 and fdone[p, b[t, p]] <= t
                    )
                    if p < P - 1:
                        assert bdone[p + 1, b[t, p]] < t
                    bdone[p, b[t, p]] = t
        for p in range(P):
            assert sorted([x for x in f[:, p] if x >= 0]) == list(range(M))
            assert sorted([x for x in b[:, p] if x >= 0]) == list(range(M))


@pytest.mark.parametrize(
    "sizes", [{"pp": 2, "dp_shard": 4}, {"pp": 4, "dp_shard": 2},
              {"pp": 2, "cp": 2, "dp_shard": 2}],
    ids=["pp2xdp4", "pp4xdp2", "pp2xcp2xdp2"],
)
@pytest.mark.slow
def test_1f1b_train_parity(sizes):
    """1F1B explicit fwd/bwd pipeline: loss + all grads match end-to-end
    autodiff of the same stacked-layer + head computation."""
    from automodel_tpu.parallel.pp import pipeline_train_1f1b

    L, H, V, B, S, M = 4, 16, 32, 16, 8, 4
    rng = np.random.default_rng(0)
    params = {
        "w1": jnp.asarray(rng.normal(0, 0.1, (L, H, H)), jnp.float32),
        "b1": jnp.zeros((L, H), jnp.float32),
    }
    head = {"w": jnp.asarray(rng.normal(0, 0.1, (H, V)), jnp.float32)}
    h0 = jnp.asarray(rng.normal(0, 1, (B, S, H)), jnp.float32)
    pos = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32)[None], (B, S))
    seg = jnp.zeros((B, S), jnp.int32)
    lab = jnp.asarray(rng.integers(0, V, (B, S)), jnp.int32)

    def layer_fn(h, lp, p, s):
        return jnp.tanh(h @ lp["w1"] + lp["b1"])

    def head_loss(h, hp, labels):
        lp_ = jax.nn.log_softmax(h @ hp["w"])
        return -jnp.sum(jnp.take_along_axis(lp_, labels[..., None], -1))

    def ref_loss(params, head, h0):
        h, _ = jax.lax.scan(
            lambda c, lp: (layer_fn(c, lp, pos, seg), None), h0, params
        )
        return head_loss(h, head, lab)

    ref, (gp_ref, gh_ref, dh_ref) = jax.value_and_grad(
        ref_loss, argnums=(0, 1, 2)
    )(params, head, h0)

    ctx = MeshConfig(**sizes).build()
    loss, dh, gl, gh = jax.jit(
        lambda *a: pipeline_train_1f1b(
            *a, layer_fn=layer_fn, head_params=head, head_loss_fn=head_loss,
            mesh_ctx=ctx, num_microbatches=M,
        )
    )(h0, pos, seg, lab, params)

    np.testing.assert_allclose(float(loss), float(ref), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(dh), np.asarray(dh_ref), rtol=2e-4, atol=1e-5)
    for a, b_ in zip(jax.tree.leaves(gl), jax.tree.leaves(gp_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), rtol=2e-4, atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(gh["w"]), np.asarray(gh_ref["w"]), rtol=2e-4, atol=1e-5
    )


def test_interleaved_schedule_tables_valid():
    """Interleaved tables: every (stage, mb) fwd+bwd exactly once,
    dependencies >=1 tick apart; compute-normalized span beats plain 1F1B
    (each interleaved tick runs 1/V of the layer work)."""
    from automodel_tpu.parallel.pp import (
        interleaved_1f1b_tables,
        one_f_one_b_tables,
    )

    for M, P, V in [(8, 2, 2), (8, 4, 2), (16, 4, 4), (4, 2, 2), (8, 2, 4)]:
        f, b = interleaved_1f1b_tables(M, P, V)
        S = P * V
        T = f.shape[0]
        fdone = np.full((S, M), 10**9)
        bdone = np.full((S, M), 10**9)
        for t in range(T):
            for p in range(P):
                if f[t, p] >= 0:
                    v, m = divmod(int(f[t, p]), M)
                    s = v * P + p
                    if s > 0:
                        assert fdone[s - 1, m] < t
                    assert fdone[s, m] == 10**9
                    fdone[s, m] = t
                if b[t, p] >= 0:
                    v, m = divmod(int(b[t, p]), M)
                    s = v * P + p
                    assert fdone[s, m] < t
                    if s < S - 1:
                        assert bdone[s + 1, m] < t
                    assert bdone[s, m] == 10**9
                    bdone[s, m] = t
        assert (fdone < 10**9).all() and (bdone < 10**9).all()
        t_plain = one_f_one_b_tables(M, P)[0].shape[0]
        assert T / V < t_plain, (M, P, V, T, t_plain)


def test_zero_bubble_schedule_tables_valid():
    """ZB-H1 tables: every (stage, mb) F, B, and W exactly once; B needs
    own F + downstream B; W needs own B; stash-capacity invariants hold
    (≤P inputs F→W, ≤P cotangents B→W — the mod-P slot correctness); the
    span does not exceed 1F1B's (W only fills idle slots)."""
    from automodel_tpu.parallel.pp import one_f_one_b_tables, zero_bubble_tables

    for M, P in [(4, 2), (8, 2), (8, 4), (16, 4), (4, 4), (6, 3)]:
        f, b, w = zero_bubble_tables(M, P)
        T = f.shape[0]
        fdone = np.full((P, M), 10**9)
        bdone = np.full((P, M), 10**9)
        wdone = np.full((P, M), 10**9)
        for t in range(T):
            for p in range(P):
                assert sum(x[t, p] >= 0 for x in (f, b, w)) <= 1  # one op/tick
                if f[t, p] >= 0:
                    m = int(f[t, p])
                    if p > 0:
                        assert fdone[p - 1, m] < t
                    assert fdone[p, m] == 10**9
                    fdone[p, m] = t
                if b[t, p] >= 0:
                    m = int(b[t, p])
                    assert fdone[p, m] < t
                    if p < P - 1:
                        assert bdone[p + 1, m] < t
                    assert bdone[p, m] == 10**9
                    bdone[p, m] = t
                if w[t, p] >= 0:
                    m = int(w[t, p])
                    assert bdone[p, m] < t
                    assert wdone[p, m] == 10**9
                    wdone[p, m] = t
        assert (fdone < 10**9).all() and (bdone < 10**9).all()
        assert (wdone < 10**9).all()
        # stash-slot collision freedom: while input m is live (F..W) no
        # other m' ≡ m (mod P) may be written; same for cotangents (B..W)
        for p in range(P):
            for m in range(M):
                for m2 in range(m + 1, M):
                    if m2 % P == m % P:
                        assert fdone[p, m2] > wdone[p, m], (M, P, p, m, m2)
                        assert bdone[p, m2] > wdone[p, m], (M, P, p, m, m2)
        # span: W adds M ops per stage into the 1F1B frame; the greedy
        # packer absorbs what fits into idle slots and appends the rest
        # (the masked-lane executor pays a constant tick cost, so span
        # is the wall-clock proxy — see pipeline_train_zb's docstring)
        t_1f1b = one_f_one_b_tables(M, P)[0].shape[0]
        assert T <= t_1f1b + M, (M, P, T, t_1f1b)


@pytest.mark.parametrize(
    "sizes", [{"pp": 2, "dp_shard": 4}, {"pp": 4, "dp_shard": 2}],
    ids=["pp2xdp4", "pp4xdp2"],
)
@pytest.mark.slow
def test_zb_train_parity(sizes):
    """Zero-bubble split-backward pipeline: loss + all grads match
    end-to-end autodiff (B computes only dx; W reproduces exactly the
    weight grads autodiff would have)."""
    from automodel_tpu.parallel.pp import pipeline_train_zb

    L, H, V, B, S, M = 4, 16, 32, 16, 8, 4
    rng = np.random.default_rng(0)
    params = {
        "w1": jnp.asarray(rng.normal(0, 0.1, (L, H, H)), jnp.float32),
        "b1": jnp.zeros((L, H), jnp.float32),
    }
    head = {"w": jnp.asarray(rng.normal(0, 0.1, (H, V)), jnp.float32)}
    h0 = jnp.asarray(rng.normal(0, 1, (B, S, H)), jnp.float32)
    pos = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32)[None], (B, S))
    seg = jnp.zeros((B, S), jnp.int32)
    lab = jnp.asarray(rng.integers(0, V, (B, S)), jnp.int32)

    def layer_fn(h, lp, p, s):
        return jnp.tanh(h @ lp["w1"] + lp["b1"])

    def head_loss(h, hp, labels):
        lp_ = jax.nn.log_softmax(h @ hp["w"])
        return -jnp.sum(jnp.take_along_axis(lp_, labels[..., None], -1))

    def ref_loss(params, head, h0):
        h, _ = jax.lax.scan(
            lambda c, lp: (layer_fn(c, lp, pos, seg), None), h0, params
        )
        return head_loss(h, head, lab)

    ref, (gp_ref, gh_ref, dh_ref) = jax.value_and_grad(
        ref_loss, argnums=(0, 1, 2)
    )(params, head, h0)

    ctx = MeshConfig(**sizes).build()
    loss, dh, gl, gh = jax.jit(
        lambda *a: pipeline_train_zb(
            *a, layer_fn=layer_fn, head_params=head, head_loss_fn=head_loss,
            mesh_ctx=ctx, num_microbatches=M,
        )
    )(h0, pos, seg, lab, params)

    np.testing.assert_allclose(float(loss), float(ref), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(dh), np.asarray(dh_ref), rtol=2e-4, atol=1e-5)
    for a, b_ in zip(jax.tree.leaves(gl), jax.tree.leaves(gp_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), rtol=2e-4, atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(gh["w"]), np.asarray(gh_ref["w"]), rtol=2e-4, atol=1e-5
    )


@pytest.mark.slow
def test_1f1b_and_zb_memory_bound_vs_gpipe():
    """The REASON 1F1B/zb exist: peak live activation memory stays O(pp)
    stashed microbatches instead of GPipe's O(M). Assert it on the compiled
    programs: at M ≫ pp the explicit-schedule paths' temp allocation must
    be well below the gpipe autodiff path's (which stashes all M boundary
    activations), and zb must stay within ~2× of 1F1B (it adds only the
    O(pp) cotangent stash)."""
    import dataclasses

    ctx = MeshConfig(pp=2, dp_shard=1).build(jax.devices()[:2])
    M = 16
    base = dataclasses.replace(
        CFG, num_layers=2, pipeline_microbatches=M, remat_policy="none",
    )
    B, S = 32, 8
    ids = jax.random.randint(jax.random.key(2), (B, S + 1), 0, 64)
    inputs, labels = ids[:, :-1], ids[:, 1:]
    params = decoder.init(base, jax.random.key(0))

    def temp_bytes(schedule):
        cfg = dataclasses.replace(base, pipeline_schedule=schedule)
        if schedule == "gpipe":
            from automodel_tpu.loss import fused_linear_cross_entropy
            from automodel_tpu.parallel.pp import pipeline_layers

            def loss_fn(p):
                h = decoder.forward(
                    p, cfg, inputs, return_hidden=True, mesh_ctx=ctx
                )
                ce, _ = fused_linear_cross_entropy(
                    h, p["lm_head"]["kernel"], labels, chunk_size=64
                )
                return ce

            fn = jax.jit(jax.grad(loss_fn))
            lowered = fn.lower(params)
        else:
            grad_fn = decoder.make_pp_1f1b_loss_and_grad(cfg, ctx, chunk_size=64)
            batch = {"input_ids": inputs, "labels": labels}
            fn = jax.jit(lambda p: grad_fn(p, batch, jax.random.key(0)))
            lowered = fn.lower(params)
        mem = lowered.compile().memory_analysis()
        return int(mem.temp_size_in_bytes)

    gpipe = temp_bytes("gpipe")
    f1b = temp_bytes("1f1b")
    zb = temp_bytes("zb")
    # gpipe stashes all M=16 boundary activations; 1f1b/zb stash ≤ pp=2
    assert f1b < 0.6 * gpipe, (f1b, gpipe)
    assert zb < 0.6 * gpipe, (zb, gpipe)
    assert zb <= 2.0 * f1b, (zb, f1b)


@pytest.mark.slow
def test_zb_matches_end_to_end_autodiff():
    """Zero-bubble through the real decoder grad path == autodiff."""
    import dataclasses

    from automodel_tpu.loss import fused_linear_cross_entropy

    cfg4 = dataclasses.replace(
        CFG, num_layers=4, pipeline_microbatches=4, pipeline_schedule="zb",
    )
    ctx = MeshConfig(pp=2, dp_shard=4).build()
    params = decoder.init(cfg4, jax.random.key(0))
    sh = logical_to_shardings(
        decoder.param_specs(cfg4), ctx,
        shapes=jax.tree.map(lambda p: p.shape, params),
    )
    sharded = jax.device_put(params, sh)
    ids = jax.random.randint(jax.random.key(2), (16, 17), 0, 64)
    inputs, labels = ids[:, :-1], ids[:, 1:]

    def ref_loss(p):
        hidden = decoder.forward(p, cfg4, inputs, return_hidden=True)
        ce, n = fused_linear_cross_entropy(
            hidden, p["lm_head"]["kernel"], labels, chunk_size=64
        )
        return ce

    ref_ce, ref_grads = jax.value_and_grad(ref_loss)(params)

    grad_fn = decoder.make_pp_1f1b_loss_and_grad(cfg4, ctx, chunk_size=64)
    batch = {
        "input_ids": jax.device_put(inputs, ctx.sharding("batch", None)),
        "labels": jax.device_put(labels, ctx.sharding("batch", None)),
    }
    grads, ce, aux = jax.jit(grad_fn)(sharded, batch, jax.random.key(0))
    np.testing.assert_allclose(float(ce), float(ref_ce), rtol=1e-5)
    for a, b, path in zip(
        jax.tree.leaves(grads), jax.tree.leaves(ref_grads),
        [str(p) for p, _ in jax.tree_util.tree_leaves_with_path(ref_grads)],
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-3, atol=2e-4, err_msg=path
        )


@pytest.mark.slow
def test_interleaved_matches_end_to_end_autodiff():
    """Interleaved-1F1B loss and grads == single-device autodiff."""
    import dataclasses

    from automodel_tpu.loss import fused_linear_cross_entropy
    from automodel_tpu.training import init_train_state, make_train_step
    from automodel_tpu.optim import OptimizerConfig
    from automodel_tpu.parallel import logical_to_shardings
    from automodel_tpu.distributed import MeshConfig

    cfg4 = dataclasses.replace(
        CFG, num_layers=4, pipeline_microbatches=4,
        pipeline_schedule="interleaved", pipeline_virtual_stages=2,
    )
    ctx = MeshConfig(pp=2, dp_shard=4).build()
    params = decoder.init(cfg4, jax.random.key(0))
    sh = logical_to_shardings(
        decoder.param_specs(cfg4), ctx,
        shapes=jax.tree.map(lambda p: p.shape, params),
    )
    sharded = jax.device_put(params, sh)
    ids = jax.random.randint(jax.random.key(2), (16, 17), 0, 64)
    inputs, labels = ids[:, :-1], ids[:, 1:]

    def ref_loss(p):
        hidden = decoder.forward(p, cfg4, inputs, return_hidden=True)
        ce, n = fused_linear_cross_entropy(
            hidden, p["lm_head"]["kernel"], labels, chunk_size=64
        )
        return ce

    ref_ce, ref_grads = jax.value_and_grad(ref_loss)(params)

    grad_fn = decoder.make_pp_1f1b_loss_and_grad(cfg4, ctx, chunk_size=64)
    batch = {
        "input_ids": jax.device_put(inputs, ctx.sharding("batch", None)),
        "labels": jax.device_put(labels, ctx.sharding("batch", None)),
    }
    grads, ce, aux = jax.jit(grad_fn)(sharded, batch, jax.random.key(0))
    np.testing.assert_allclose(float(ce), float(ref_ce), rtol=1e-5)
    for a, b, path in zip(
        jax.tree.leaves(grads), jax.tree.leaves(ref_grads),
        [str(p) for p, _ in jax.tree_util.tree_leaves_with_path(ref_grads)],
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-3, atol=2e-4, err_msg=path
        )


@pytest.mark.parametrize("schedule", ["1f1b", "zb"])
def test_qat_composes_with_explicit_pp_grads(schedule):
    """The QAT×PP fence is gone: make_train_step composes the fake-quant
    param transform with an explicit pipeline grad_fn by vjp of the
    transform around the pipeline's grads (the straight-through estimator
    makes that vjp a masked identity). One sgd(1.0) step through the pp2
    pipeline must land on the same params as autodiff of
    loss(fake_quant(params)) on a single device."""
    import dataclasses

    import optax

    from automodel_tpu.loss import fused_linear_cross_entropy
    from automodel_tpu.ops.quant import QATConfig
    from automodel_tpu.training import (
        TrainStepConfig,
        init_train_state,
        make_train_step,
    )

    cfg = dataclasses.replace(CFG, pipeline_schedule=schedule)
    ctx = MeshConfig(pp=2, dp_shard=4).build()
    params = decoder.init(cfg, jax.random.key(0))
    sh = logical_to_shardings(
        decoder.param_specs(cfg), ctx,
        shapes=jax.tree.map(lambda p: p.shape, params),
    )
    sharded = jax.device_put(params, sh)
    ids = jax.random.randint(jax.random.key(2), (16, 17), 0, 64)
    inputs, labels = ids[:, :-1], ids[:, 1:]
    transform = QATConfig(enabled=True, precision="int8").make_param_transform()

    # single-device reference: autodiff THROUGH the fake-quant transform
    def ref_loss(p):
        qp = transform(p, jnp.int32(0))
        hidden = decoder.forward(qp, cfg, inputs, return_hidden=True)
        ce, n = fused_linear_cross_entropy(
            hidden, qp["lm_head"]["kernel"], labels, chunk_size=64
        )
        return ce / n, n

    (ref_ce, _), ref_grads = jax.value_and_grad(ref_loss, has_aux=True)(params)
    expected = jax.tree.map(lambda p, g: p - g, params, ref_grads)

    grad_fn = decoder.make_pp_1f1b_loss_and_grad(cfg, ctx, chunk_size=64)
    tx = optax.sgd(1.0)
    step = jax.jit(make_train_step(
        None, tx, config=TrainStepConfig(max_grad_norm=None),
        param_transform=transform, grad_fn=grad_fn,
    ))
    state = init_train_state(sharded, tx)
    batch = {
        "input_ids": jax.device_put(
            inputs[None], ctx.sharding(None, "batch", None)),
        "labels": jax.device_put(
            labels[None], ctx.sharding(None, "batch", None)),
    }
    state, metrics = step(state, batch, jax.random.key(0))
    np.testing.assert_allclose(float(metrics["loss"]), float(ref_ce), rtol=1e-5)
    for a, b, path in zip(
        jax.tree.leaves(state.params), jax.tree.leaves(expected),
        [str(p) for p, _ in jax.tree_util.tree_leaves_with_path(expected)],
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-3, atol=2e-4, err_msg=path
        )
