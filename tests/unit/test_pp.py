"""Pipeline-parallel tests on the virtual 8-device mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from automodel_tpu.distributed import MeshConfig
from automodel_tpu.loss import cross_entropy_sum
from automodel_tpu.models.llm import decoder
from automodel_tpu.models.llm.decoder import TransformerConfig
from automodel_tpu.parallel import logical_to_shardings

CFG = TransformerConfig(
    vocab_size=64,
    hidden_size=32,
    intermediate_size=48,
    num_layers=4,
    num_heads=4,
    num_kv_heads=2,
    dtype=jnp.float32,
    remat_policy="none",
    pipeline_microbatches=4,
)


def _setup(pp, dp):
    ctx = MeshConfig(pp=pp, dp_shard=dp).build(jax.devices()[: pp * dp])
    params = decoder.init(CFG, jax.random.key(0))
    sh = logical_to_shardings(
        decoder.param_specs(CFG), ctx, shapes=jax.tree.map(lambda p: p.shape, params)
    )
    return ctx, params, jax.device_put(params, sh)


@pytest.mark.parametrize("pp,dp", [(2, 1), (4, 1), (2, 4)])
def test_pp_forward_matches_single_device(pp, dp):
    ctx, params, sharded = _setup(pp, dp)
    B = max(4, 4 * dp)
    ids = jax.random.randint(jax.random.key(1), (B, 16), 0, 64)
    ref = decoder.forward(params, CFG, ids)

    @jax.jit
    def f(p, i):
        return decoder.forward(p, CFG, i, mesh_ctx=ctx)

    out = f(sharded, jax.device_put(ids, ctx.sharding("batch", None)))
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out), rtol=2e-4, atol=2e-4)


def test_pp_layer_stack_is_stage_sharded():
    ctx, _, sharded = _setup(4, 2)
    k = sharded["layers"]["q_proj"]["kernel"]
    assert k.sharding.spec[0] == "pp"
    # each stage holds 1/4 of the layers
    assert k.addressable_shards[0].data.shape[0] == 1


def test_pp_backward_matches_single_device():
    ctx, params, sharded = _setup(2, 2)
    ids = jax.random.randint(jax.random.key(2), (8, 17), 0, 64)
    inputs, labels = ids[:, :-1], ids[:, 1:]

    def loss(p, mesh):
        logits = decoder.forward(p, CFG, inputs, mesh_ctx=mesh)
        s, n = cross_entropy_sum(logits, labels)
        return s / n

    g_ref = jax.grad(lambda p: loss(p, None))(params)
    g_pp = jax.jit(jax.grad(lambda p: loss(p, ctx)))(sharded)
    for a, b in zip(jax.tree.leaves(g_ref), jax.tree.leaves(g_pp)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-3, atol=2e-4)


def test_pp_rejects_tp():
    ctx = MeshConfig(pp=2, tp=2, dp_shard=2).build()
    params = decoder.init(CFG, jax.random.key(0))
    with pytest.raises(NotImplementedError):
        decoder.forward(params, CFG, jnp.zeros((4, 16), jnp.int32), mesh_ctx=ctx)
