"""The unified observability layer's acceptance contract (docs/OBSERVABILITY.md):

- registry units: counter/gauge/histogram semantics, label series, the
  kind-conflict tripwire, and the Prometheus text round-trip of every
  cataloged metric (METRIC_CATALOG ↔ docs table ↔ snapshot_prometheus);
- tracer units: span nesting validated through the Chrome export, the
  bounded flight-recorder ring, and the deterministic lifecycle digest
  (wall clocks / step indices / stream backpressure edges excluded);
- serving integration: tracing ON changes neither the greedy token stream
  nor the compile count; two identical online load_test runs produce the
  SAME digest; disagg TTFT attribution components sum to the measured
  TTFT exactly; an injected serve-step crash dumps the flight recorder.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from automodel_tpu.models.llm import decoder
from automodel_tpu.models.llm.decoder import TransformerConfig
from automodel_tpu.observability import (
    METRIC_CATALOG,
    NULL_TRACER,
    MetricsRegistry,
    Observability,
    ObservabilityConfig,
    Tracer,
    attribute_ttft,
    attribution_summary,
    build_timelines,
    validate_chrome_trace,
)
from automodel_tpu.observability.metrics import Counter, Gauge, Histogram
from automodel_tpu.resilience.faults import FaultCrash, injected
from automodel_tpu.serving import (
    DisaggConfig,
    DisaggRouter,
    Request,
    ServingConfig,
    ServingEngine,
)
from automodel_tpu.serving.frontend import FrontendConfig
from automodel_tpu.serving.load_test import LoadTestConfig, run_load_test

CFG = TransformerConfig(
    vocab_size=64, hidden_size=32, intermediate_size=48, num_layers=2,
    num_heads=4, num_kv_heads=2, qk_norm=True, dtype=jnp.float32,
    remat_policy="none",
)


@pytest.fixture(scope="module")
def params():
    return decoder.init(CFG, jax.random.key(0))


def _sc(**kw):
    return ServingConfig(
        page_size=4, num_pages=32, max_slots=3, pages_per_slot=6,
        token_budget=8, prefill_chunk=4, **kw,
    )


def _reqs(lens, seed0=0, max_new=6):
    return [
        Request(
            prompt=[int(t) for t in
                    np.random.default_rng(seed0 + i).integers(1, 64, (l,))],
            max_new_tokens=max_new,
        )
        for i, l in enumerate(lens)
    ]


# -- registry units ----------------------------------------------------------


def test_counter_gauge_histogram_semantics():
    c = Counter()
    c.inc()
    c.inc(2.5)
    assert c.value == 3.5
    with pytest.raises(ValueError):
        c.inc(-1)
    g = Gauge()
    g.set(5)
    g.inc()
    g.dec(2)
    assert g.value == 4.0
    h = Histogram(bounds=(1.0, 10.0, 100.0))
    for v in (0.5, 5.0, 50.0, 500.0):
        h.observe(v)
    assert h.count == 4 and h.sum == 555.5
    snap = h.snapshot()
    assert snap["cumulative"] == [1, 2, 3]  # le-semantics; 500 overflows
    assert h.percentile(0.5) == 10.0
    assert h.percentile(1.0) == 100.0  # overflow reports the top bound
    with pytest.raises(ValueError):
        Histogram(bounds=(1.0, 1.0))  # must strictly increase


def test_registry_kind_conflict_and_label_series():
    reg = MetricsRegistry()
    reg.counter("x_total", "things").inc()
    with pytest.raises(TypeError):
        reg.gauge("x_total")
    reg.counter("shed_total", "sheds", reason="deadline").inc(2)
    reg.counter("shed_total", "sheds", reason="queue_full").inc()
    snap = reg.snapshot()
    assert snap['shed_total{reason="deadline"}'] == 2.0
    assert snap['shed_total{reason="queue_full"}'] == 1.0
    assert list(snap) == sorted(snap)  # deterministic key order


def test_prometheus_exposition_shape():
    reg = MetricsRegistry()
    reg.counter("a_total", "a counter").inc(3)
    reg.histogram("lat_ms", "latency", buckets=(1.0, 10.0)).observe(5.0)
    text = reg.snapshot_prometheus()
    assert "# HELP a_total a counter" in text
    assert "# TYPE a_total counter" in text
    assert "a_total 3" in text
    assert 'lat_ms_bucket{le="1"} 0' in text
    assert 'lat_ms_bucket{le="10"} 1' in text
    assert 'lat_ms_bucket{le="+Inf"} 1' in text
    assert "lat_ms_sum 5" in text and "lat_ms_count 1" in text


def test_metric_catalog_roundtrips_docs_and_prometheus():
    """Every cataloged metric appears in docs/OBSERVABILITY.md's catalog
    table AND in the Prometheus snapshot of a catalog-registered registry;
    the docs table carries no phantom metrics either."""
    reg = MetricsRegistry()
    reg.register_catalog()
    text = reg.snapshot_prometheus()
    for name, kind, _help in METRIC_CATALOG:
        assert f"# TYPE {name} {kind}" in text, name
    doc = os.path.join(os.path.dirname(__file__), "..", "..", "docs",
                       "OBSERVABILITY.md")
    with open(doc, encoding="utf-8") as f:
        rows = [ln for ln in f if ln.startswith("| `")]
    documented = {ln.split("`")[1] for ln in rows}
    assert documented == {name for name, _k, _h in METRIC_CATALOG}


# -- tracer units ------------------------------------------------------------


def test_tracer_span_nesting_and_exports(tmp_path):
    tr = Tracer(ring_len=4)
    with tr.span("step.run", track="engine", step=0):
        with tr.span("step.absorb", track="engine", step=0):
            tr.instant("request.commit", track="engine", step=0, rid=1, n=1)
    tr.instant("request.done", track="other", rid=1, reason="eos")
    chrome = tmp_path / "t.trace.json"
    tr.export_chrome(str(chrome))
    stats = validate_chrome_trace(str(chrome))
    assert stats == {"events": 6, "spans": 2, "instants": 2, "tracks": 1}
    jsonl = tmp_path / "t.trace.jsonl"
    tr.export_jsonl(str(jsonl))
    lines = [json.loads(ln) for ln in jsonl.read_text().splitlines()]
    assert len(lines) == 4
    assert {ln["name"] for ln in lines} == {
        "step.run", "step.absorb", "request.commit", "request.done",
    }
    # the outer span closes after the inner: X events record on exit,
    # so the inner one appears first but nests by [ts, ts+dur]
    spans = {ln["name"]: ln for ln in lines if "dur_us" in ln}
    inner, outer = spans["step.absorb"], spans["step.run"]
    assert outer["ts_us"] <= inner["ts_us"]
    assert inner["ts_us"] + inner["dur_us"] <= outer["ts_us"] + outer["dur_us"]


def test_flight_ring_is_bounded():
    tr = Tracer(ring_len=8)
    for i in range(50):
        tr.instant("request.commit", rid=i)
    assert len(tr.events) == 50
    assert len(tr.ring) == 8
    assert [e.rid for e in tr.ring] == list(range(42, 50))


def test_null_tracer_is_inert():
    assert NULL_TRACER.events == ()
    NULL_TRACER.instant("request.submit", rid=0)
    with NULL_TRACER.span("step.run", step=3):
        pass
    assert NULL_TRACER.events == ()


def test_digest_excludes_timing_and_stream_edges():
    def fill(tr, *, shift, with_pause):
        tr.instant("request.submit", rid=0, step=1 + shift, prompt_len=4)
        if with_pause:
            tr.instant("stream.pause", rid=0, step=2 + shift)
            tr.instant("stream.resume", rid=0, step=3 + shift)
        tr.instant("request.done", rid=0, step=9 + shift, reason="eos")
        tr.instant("step.plan", rid=-1)  # rid-less events never count

    a, b = Tracer(), Tracer()
    fill(a, shift=0, with_pause=True)
    fill(b, shift=5, with_pause=False)
    assert a.digest() == b.digest()
    c = Tracer()
    c.instant("request.submit", rid=0, step=1, prompt_len=5)  # arg differs
    c.instant("request.done", rid=0, step=9, reason="eos")
    assert c.digest() != a.digest()


# -- serving integration -----------------------------------------------------


def test_tracing_on_off_parity_and_compile_once(params):
    """The observability contract's heart: switching tracing ON changes
    neither the greedy token stream nor the number of compiled step
    signatures, and the trace actually recorded the run."""
    reqs = lambda: _reqs([5, 9, 3], seed0=10)  # noqa: E731
    base = ServingEngine(params, CFG, _sc()).serve_batch(reqs())
    sc = _sc(observability=ObservabilityConfig(enabled=True))
    eng = ServingEngine(params, CFG, sc)
    res = eng.serve_batch(reqs())
    assert res["outputs"] == base["outputs"]
    assert res["stats"]["compiled_signatures"] == 1
    assert base["stats"]["compiled_signatures"] == 1
    names = {e.name for e in eng.obs.tracer.events}
    assert {"step.plan", "step.run", "step.absorb", "request.submit",
            "request.admit", "request.first_token", "request.done"} <= names
    reg = eng.obs.registry.snapshot()
    assert reg["serve_steps_total"] > 0
    assert reg["serve_new_tokens_total"] == sum(
        len(o) for o in res["outputs"]
    )
    assert reg["serve_step_ms"]["count"] == reg["serve_steps_total"]


def test_digest_stable_across_identical_load_tests(params):
    """Two fresh engines driving the SAME deterministic online trace
    produce the same lifecycle digest even though wall-clock timings (and
    hence idle turns / pause edges) differ run to run."""
    lt = LoadTestConfig(
        num_requests=8, prompt_len=(3, 8), max_new_tokens=5,
        mean_interarrival_steps=0.5, seed=3,
    )
    fc = FrontendConfig(idle_sleep_s=0.0002, stream_buffer=64)
    digests = []
    for _ in range(2):
        eng = ServingEngine(
            params, CFG, _sc(observability=ObservabilityConfig(enabled=True)),
        )
        report = run_load_test(eng, lt, fc)
        assert report["completed"] == 8
        digests.append(eng.obs.tracer.digest())
    assert digests[0] == digests[1]


def test_disagg_timeline_phases_sum_to_ttft(params):
    """Disagg run with handoffs: every first-token request's attribution
    components (queue + prefill + transfer + step + backpressure) sum to
    its measured TTFT exactly, and the handoff made the transfer phase
    real (markers present, not zero-width by omission)."""
    sc = _sc(observability=ObservabilityConfig(enabled=True))
    dc = DisaggConfig(enabled=True, transfer_pages=4, prefill_token_budget=16)
    router = DisaggRouter(params, CFG, sc, dc)
    res = router.serve_batch(_reqs([5, 11, 3, 7], seed0=30))
    assert res["stats"]["handoffs"] == 4
    events = list(router.obs.tracer.events)
    assert any(e.name == "kv_transfer" and e.ph == "X" for e in events)
    tls = build_timelines(events)
    spans = sorted(
        (e.ts, e.ts + e.dur) for e in events
        if e.ph == "X" and e.name == "step.run"
    )
    checked = 0
    for tl in tls.values():
        att = attribute_ttft(tl, spans)
        if att is None:
            continue
        total = (att["queue_ms"] + att["prefill_ms"] + att["transfer_ms"]
                 + att["step_ms"] + att["backpressure_ms"])
        assert total == pytest.approx(att["ttft_ms"], abs=1e-6)
        assert tl.t_extract is not None and tl.t_handoff_admit is not None
        checked += 1
    assert checked == 4
    summary = attribution_summary(events)
    assert summary["with_first_token"] == 4
    assert summary["ttft_p50"]["transfer_ms"] >= 0.0


def test_flight_recorder_dumps_on_injected_crash(params, tmp_path):
    """An injected serve-step FaultCrash (a BaseException, like a real
    preemption) escapes serve_batch — but not before the flight recorder
    writes its ring of the last events before the failure."""
    dump = tmp_path / "flight.jsonl"
    sc = _sc(observability=ObservabilityConfig(
        enabled=True, flight_recorder_len=32,
        flight_recorder_path=str(dump),
    ))
    eng = ServingEngine(params, CFG, sc)
    with injected({"point": "serve_step", "mode": "crash", "step": 2}):
        with pytest.raises(FaultCrash):
            eng.serve_batch(_reqs([5, 7], seed0=50))
    assert dump.exists()
    lines = [json.loads(ln) for ln in dump.read_text().splitlines()]
    assert lines[0]["flight_recorder"] is True
    assert lines[0]["reason"] == "crash"
    assert lines[0]["events"] == len(lines) - 1 > 0
    assert {"step.plan", "step.run"} <= {ln["name"] for ln in lines[1:]}
    snap = eng.obs.registry.snapshot()
    assert snap['flight_recorder_dumps_total{reason="crash"}'] == 1.0


def test_observability_disabled_is_null_tracer(params):
    """Default config: the engine gets the null tracer (no events, no
    ring) while the registry still mirrors the run's stats."""
    eng = ServingEngine(params, CFG, _sc())
    res = eng.serve_batch(_reqs([4, 6], seed0=70))
    assert eng.obs.tracer is NULL_TRACER
    assert eng.obs.enabled is False
    assert eng.obs.registry.snapshot()["serve_new_tokens_total"] == sum(
        len(o) for o in res["outputs"]
    )


def test_observability_export_writes_both_faces(tmp_path):
    obs = Observability(ObservabilityConfig(
        enabled=True, trace_path=str(tmp_path / "run" / "serve"),
    ))
    with obs.tracer.span("step.run", step=0):
        obs.tracer.instant("request.commit", rid=0, step=0, n=1)
    paths = obs.export()
    assert set(paths) == {"chrome", "jsonl"}
    assert validate_chrome_trace(paths["chrome"])["spans"] == 1
    assert len(open(paths["jsonl"]).read().splitlines()) == 2
    # disabled bundles export nothing
    assert Observability(None).export(str(tmp_path / "x")) == {}
