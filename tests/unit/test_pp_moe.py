"""PP×EP: MoE expert dispatch inside the pipeline shard_map.

The flagship composition (DeepSeek-V3 PP4×EP64, Kimi-K2 PP8×EP32 per
BASELINE.md): dropless expert dispatch runs inside each pipeline stage's
step — the ep all-to-all is confined to that stage so it overlaps other
stages' compute — under both the GPipe (autodiff) and explicit-gradient
(1F1B / ZB-H1 / interleaved) schedules."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from automodel_tpu.distributed import MeshConfig
from automodel_tpu.loss import fused_linear_cross_entropy
from automodel_tpu.models.llm import decoder
from automodel_tpu.models.moe_lm import decoder as moe_decoder
from automodel_tpu.models.moe_lm.decoder import MoETransformerConfig
from automodel_tpu.moe import MoEConfig
from automodel_tpu.parallel import logical_to_shardings

CFG = MoETransformerConfig(
    vocab_size=64,
    hidden_size=32,
    intermediate_size=48,
    num_layers=2,
    num_heads=4,
    num_kv_heads=2,
    first_k_dense=0,  # the pipelined stack must be uniform
    moe=MoEConfig(
        n_routed_experts=4,
        n_shared_experts=1,
        experts_per_token=2,
        moe_intermediate_size=16,
        shared_expert_intermediate_size=16,
        aux_loss_coeff=0.01,
        dispatcher="dropless",
    ),
    dtype=jnp.float32,
    remat_policy="none",
    pipeline_microbatches=2,
)


def _setup(cfg, sizes):
    ctx = MeshConfig(**sizes).build()
    params = moe_decoder.init(cfg, jax.random.key(0))
    sh = logical_to_shardings(
        moe_decoder.param_specs(cfg), ctx,
        shapes=jax.tree.map(lambda p: p.shape, params),
    )
    return ctx, params, jax.device_put(params, sh)


def _batch(ctx, B=8, S=17):
    ids = jax.random.randint(jax.random.key(2), (B, S), 0, 64)
    inputs, labels = ids[:, :-1], ids[:, 1:]
    return (
        jax.device_put(inputs, ctx.sharding("batch", None)),
        jax.device_put(labels, ctx.sharding("batch", None)),
    )


@pytest.mark.slow
def test_moe_gpipe_pipeline_matches_single_device():
    """GPipe pipelined MoE forward (expert A2A inside each stage's step)
    == the GSPMD layer scan on one device — logits exactly; the aux
    load-balance loss only in order of magnitude (the pipeline computes
    the per-microbatch chunk-mean estimator, the global gate a product of
    whole-batch means — not the same statistic)."""
    cfg = dataclasses.replace(CFG, num_layers=4, pipeline_microbatches=4)
    ctx, params, sharded = _setup(cfg, {"pp": 2, "ep": 2, "dp_shard": 2})
    ids = jax.random.randint(jax.random.key(1), (16, 8), 0, 64)
    ref, ref_aux = moe_decoder.forward(params, cfg, ids)

    ids_in = jax.device_put(ids, ctx.sharding("batch", None))
    out, aux = jax.jit(
        lambda p, i: moe_decoder.forward(p, cfg, i, mesh_ctx=ctx)
    )(sharded, ids_in)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out), rtol=2e-3, atol=2e-3)
    assert 0.2 < float(aux) / float(ref_aux) < 5.0, (float(aux), float(ref_aux))

    # grads THROUGH the pipelined dispatch (autodiff over the shard_map,
    # ragged A2A transpose included) == single-device autodiff
    def loss(p, mesh, i):
        h, a = moe_decoder.forward(p, cfg, i, mesh_ctx=mesh, return_hidden=True)
        return jnp.mean(h**2) + 0.01 * a

    g_ref = jax.grad(lambda p: loss(p, None, ids))(params)
    g_pp = jax.jit(jax.grad(lambda p: loss(p, ctx, ids_in)))(sharded)
    for (path, a), (_, b) in zip(
        jax.tree_util.tree_leaves_with_path(g_ref),
        jax.tree_util.tree_leaves_with_path(g_pp),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-3, atol=3e-4,
            err_msg=jax.tree_util.keystr(path),
        )


def test_moe_gpipe_pipeline_threads_token_mask():
    """Pad tokens stay out of routing / aux stats on the pipelined GPipe
    forward, matching the GSPMD scan (the recipe always passes
    token_mask=(labels != -100) for MoE): tokens_per_expert counts only
    mask-True tokens, and the masked aux tracks the GSPMD value."""
    ctx, params, sharded = _setup(CFG, {"pp": 2, "ep": 2})
    B, S = 8, 16
    ids = jax.random.randint(jax.random.key(3), (B, S), 0, 64)
    mask = np.array(jax.random.bernoulli(jax.random.key(4), 0.75, (B, S)))
    mask[0, 0] = True  # keep at least one routed token per program
    ids_in = jax.device_put(ids, ctx.sharding("batch", None))
    mask_in = jax.device_put(jnp.asarray(mask), ctx.sharding("batch", None))

    fwd = jax.jit(
        lambda p, i, m: moe_decoder.forward(
            p, CFG, i, mesh_ctx=ctx, token_mask=m, return_stats=True
        )
    )
    _, aux_m, stats = fwd(sharded, ids_in, mask_in)
    K, E, L = CFG.moe.experts_per_token, CFG.moe.n_routed_experts, CFG.num_layers
    tpe = np.asarray(stats["tokens_per_expert"])
    assert tpe.shape == (L, E)
    assert float(tpe.sum()) == mask.sum() * K * L  # pad (token, slot)s dropped

    # all-True mask keeps every (token, slot); and the masked aux is the
    # same statistic the (mask-honoring) GSPMD scan computes, up to the
    # chunk-mean-vs-global estimator difference
    ones = jax.device_put(jnp.ones((B, S), bool), ctx.sharding("batch", None))
    _, _, stats_u = fwd(sharded, ids_in, ones)
    assert float(np.asarray(stats_u["tokens_per_expert"]).sum()) == B * S * K * L
    _, ref_aux = moe_decoder.forward(params, CFG, ids, token_mask=jnp.asarray(mask))
    assert 0.2 < float(aux_m) / float(ref_aux) < 5.0, (float(aux_m), float(ref_aux))


@pytest.mark.parametrize("sched", ["1f1b", "zb"])
def test_moe_explicit_schedule_matches_gpipe_autodiff(sched):
    """ISSUE 1 acceptance: explicit 1F1B / ZB-H1 gradients on a tiny MoE ==
    end-to-end autodiff over the (pipelined) GPipe path. Both run the same
    per-chunk aux estimator, so loss AND grads match to float32 noise."""
    _run_explicit_schedule_parity(sched)


@pytest.mark.slow
def test_moe_explicit_interleaved_matches_gpipe_autodiff():
    _run_explicit_schedule_parity("interleaved", num_layers=4, virtual=2)


def _run_explicit_schedule_parity(sched, num_layers=2, virtual=1):
    # fake_balanced_gate pins the routing: live top-k is discontinuous, so
    # two differently-compiled-but-equivalent programs (explicit schedule vs
    # GPipe autodiff) can flip near-tie expert assignments on ~1e-7
    # activation noise and diverge by O(1) — the dispatch/A2A/expert-grad
    # machinery under test is identical either way
    cfg = dataclasses.replace(
        CFG, num_layers=num_layers, pipeline_schedule=sched,
        pipeline_virtual_stages=virtual,
        moe=dataclasses.replace(CFG.moe, fake_balanced_gate=True),
    )
    ctx, params, sharded = _setup(cfg, {"pp": 2, "ep": 2})
    inputs, labels = _batch(ctx)
    n = float(np.sum(np.asarray(labels) != -100))

    def ref_loss(p):
        hidden, aux = moe_decoder.forward(
            p, cfg, inputs, mesh_ctx=ctx, return_hidden=True
        )
        ce, _ = fused_linear_cross_entropy(
            hidden, p["lm_head"]["kernel"], labels, chunk_size=64
        )
        return ce + aux * n  # the combine_losses contract

    ref_ce, ref_grads = jax.jit(jax.value_and_grad(ref_loss))(sharded)

    grad_fn = decoder.make_pp_1f1b_loss_and_grad(cfg, ctx, chunk_size=64)
    batch = {"input_ids": inputs, "labels": labels}
    grads, ce, aux = jax.jit(grad_fn)(sharded, batch, jax.random.key(0))

    np.testing.assert_allclose(float(ce), float(ref_ce), rtol=1e-5)
    tpe = aux["tokens_per_expert"]
    assert tpe.shape == (cfg.num_layers, cfg.moe.n_routed_experts)
    # every (token, slot) routed exactly once per MoE layer
    assert float(tpe.sum()) == inputs.size * cfg.moe.experts_per_token * cfg.num_layers
    for (path, a), (_, b) in zip(
        jax.tree_util.tree_leaves_with_path(grads),
        jax.tree_util.tree_leaves_with_path(ref_grads),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-3, atol=2e-4,
            err_msg=jax.tree_util.keystr(path),
        )


@pytest.mark.parametrize("sched", ["1f1b", "zb"])
def test_layer_aux_contract_parity(sched):
    """The layer-aux plumbing itself (aux_scale fold-in, extras
    accumulation, aux grads through the explicit bwd) against autodiff over
    pipeline_layers, with a smooth synthetic aux layer — no top-k
    discontinuity, so this parity is exact by construction and complements
    the routing-pinned MoE test above."""
    from automodel_tpu.loss import fused_linear_cross_entropy
    from automodel_tpu.parallel.pp import (
        pipeline_layers,
        pipeline_train_1f1b,
        pipeline_train_zb,
    )

    ctx = MeshConfig(pp=2, ep=2, dp_shard=2).build()
    B, S, H, M, L = 8, 16, 32, 2, 4
    ks = jax.random.split(jax.random.key(0), 4)
    layers = {"w": jax.random.normal(ks[0], (L, H), jnp.float32) * 0.1}
    head = {"kernel": jax.random.normal(ks[1], (H, 64), jnp.float32) * 0.05}
    h = jax.random.normal(ks[2], (B, S, H), jnp.float32)
    lab = jax.random.randint(ks[3], (B, S), 0, 64)
    pos = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32)[None], (B, S))
    seg = jnp.zeros_like(pos)
    h, pos, seg, lab = (
        jax.device_put(h, ctx.sharding("batch", None, None)),
        jax.device_put(pos, ctx.sharding("batch", None)),
        jax.device_put(seg, ctx.sharding("batch", None)),
        jax.device_put(lab, ctx.sharding("batch", None)),
    )
    lspecs = {"w": ("layers", None)}
    ex_specs = {"stat": jax.sharding.PartitionSpec("pp", None)}
    SCALE, n_chunks = 7.0, M * 4  # dp_shard·ep·cp data chunks per microbatch

    def layer_fn(hh, lp, p_, s_):
        y = hh * (1.0 + 0.01 * lp["w"][None, None, :])
        aux = (y.astype(jnp.float32) ** 2).mean() * 0.01
        return y, aux, {"stat": jnp.ones((2,), jnp.float32)}

    def head_loss(h_mb, head_p, lab_mb):
        ce, _ = fused_linear_cross_entropy(
            h_mb, head_p["kernel"], lab_mb, chunk_size=64
        )
        return ce.astype(jnp.float32)

    def ref_loss(lp, hd):
        out, aux, _ = pipeline_layers(
            h, pos, seg, lp, layer_fn, ctx, M, remat_policy="none",
            param_logical_specs=lspecs, layer_aux=True, extras_specs=ex_specs,
        )
        mb = out.reshape(M, B // M, S, H)
        lab_mb = lab.reshape(M, B // M, S)
        ce = sum(head_loss(mb[i], hd, lab_mb[i]) for i in range(M))
        return ce + aux * SCALE * n_chunks  # chunk-mean × per-chunk scale

    ref, (g_ref, gh_ref) = jax.jit(
        jax.value_and_grad(ref_loss, argnums=(0, 1))
    )(layers, head)

    train = pipeline_train_1f1b if sched == "1f1b" else pipeline_train_zb
    loss, dh, gl, gh, ex = jax.jit(lambda lp, hd: train(
        h, pos, seg, lab, lp, layer_fn, hd, head_loss, ctx, M,
        param_logical_specs=lspecs, aux_scale=jnp.float32(SCALE),
        extras_specs=ex_specs,
    ))(layers, head)

    np.testing.assert_allclose(float(loss), float(ref), rtol=1e-5)
    # every (layer, microbatch, data-chunk) contributes one ones(2) stat
    np.testing.assert_allclose(np.asarray(ex["stat"]), 8.0)
    for a, b in zip(jax.tree.leaves(gl), jax.tree.leaves(g_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-3, atol=2e-4)
    for a, b in zip(jax.tree.leaves(gh), jax.tree.leaves(gh_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-3, atol=2e-4)


def test_moe_pipeline_rejects_first_k_dense():
    cfg = dataclasses.replace(CFG, first_k_dense=1, pipeline_schedule="1f1b")
    ctx = MeshConfig(pp=2, ep=2).build()
    with pytest.raises(NotImplementedError, match="first_k_dense"):
        decoder.make_pp_1f1b_loss_and_grad(cfg, ctx)(
            None, {"input_ids": jnp.zeros((4, 8), jnp.int32),
                   "labels": jnp.zeros((4, 8), jnp.int32)},
            jax.random.key(0),
        )


def test_moe_pipeline_rejects_capacity_dispatcher():
    from automodel_tpu.models.moe_lm.decoder import _pp_moe_layer_setup

    cfg = dataclasses.replace(
        CFG, moe=dataclasses.replace(CFG.moe, dispatcher="capacity")
    )
    ctx = MeshConfig(pp=2, ep=2).build()
    with pytest.raises(NotImplementedError, match="dropless"):
        _pp_moe_layer_setup(None, cfg, ctx, lambda w: None)


def test_grad_fn_fence_is_empty():
    """The _make_grad_fn fence list is EMPTY: MoE, PEFT, and QAT all build a
    grad_fn on the explicit schedules. QAT composes one level up — in
    make_train_step, by vjp of the fake-quant transform around the pipeline
    grads — so _make_grad_fn has nothing left to refuse."""
    from types import SimpleNamespace

    from automodel_tpu.config import ConfigNode
    from automodel_tpu.recipes.llm.train_ft import (
        TrainFinetuneRecipeForNextTokenPrediction as R,
    )

    cfg = dataclasses.replace(CFG, pipeline_schedule="1f1b")
    ctx = MeshConfig(pp=2, ep=2).build()

    def fake(qat=False, peft=None, moe=True):
        return SimpleNamespace(
            mesh_ctx=ctx, model_cfg=cfg, is_moe=moe, peft_cfg=peft,
            cfg=ConfigNode({"qat": {"enabled": qat}, "loss": {"chunk_size": 64}}),
        )

    assert callable(R._make_grad_fn(fake()))  # MoE: lifted
    assert callable(R._make_grad_fn(fake(peft=SimpleNamespace())))  # PEFT: lifted
    assert callable(R._make_grad_fn(fake(qat=True)))  # QAT: lifted (this PR)
