"""DFlash block-parallel speculative draft: mask semantics, block loss,
export round-trip, training recipe, and lossless offline decode.

Reference: nemo_automodel/components/speculative/dflash/ +
attention/dflash_mask.py + recipes/llm/train_dflash.py.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from automodel_tpu.speculative.dflash import (
    DFlashConfig,
    build_target_layer_ids,
    dflash_block_loss,
    dflash_mask,
    doc_remaining_from_segments,
    drafter_from_hf,
    drafter_to_hf,
    init_drafter,
    sample_anchors,
)

TINY = DFlashConfig(
    vocab_size=128, hidden_size=32, intermediate_size=64,
    num_heads=4, num_kv_heads=2, num_layers=2, head_dim=8,
    num_target_layers_used=2, block_size=4, num_anchors=6,
    mask_token_id=0, loss_decay_gamma=2.0,
)


def test_mask_semantics():
    """Pinned to dflash_mask.py: ctx strictly before the anchor, own block
    only, bidirectional in-block (DFlash) vs in-block-causal (JetSpec),
    padding blocks keep in-block rows non-empty."""
    anchors = jnp.asarray([[3, 7]])
    keep = jnp.asarray([[True, False]])
    S, bs = 10, 4
    m = np.asarray(dflash_mask(anchors, keep, S, bs, causal=False))
    assert m.shape == (1, 8, S + 8)
    # block 0 (queries 0-3): ctx < 3 visible, 3.. not
    assert m[0, 0, :3].all() and not m[0, 0, 3:S].any()
    assert m[0, 3, :3].all() and not m[0, 3, 3:S].any()
    # in-block bidirectional; other block invisible
    assert m[0, 0, S : S + 4].all() and not m[0, 0, S + 4 :].any()
    assert m[0, 3, S : S + 4].all()
    # padding block 1: NO ctx, but keeps its own block (no empty rows)
    assert not m[0, 4, :S].any()
    assert m[0, 4, S + 4 : S + 8].all()
    assert m.any(axis=-1).all()  # no fully-masked query row

    mc = np.asarray(dflash_mask(anchors, keep, S, bs, causal=True))
    # JetSpec: in-block causal — query offset 1 sees offsets 0,1 only
    assert mc[0, 1, S : S + 2].all() and not mc[0, 1, S + 2 : S + 4].any()

    # packed-doc gating: ctx restricted to the anchor's document
    ctx_doc = jnp.asarray([[0, 0, 0, 0, 0, 1, 1, 1, 1, 1]])
    anchor_doc = jnp.asarray([[1, 1]])
    anchors2 = jnp.asarray([[7, 7]])
    md = np.asarray(dflash_mask(
        anchors2, jnp.asarray([[True, True]]), S, bs, False,
        ctx_doc=ctx_doc, anchor_doc=anchor_doc,
    ))
    # anchor 7 in doc 1: sees ctx 5,6 (doc 1, < 7) but NOT doc 0 tokens
    assert md[0, 0, 5:7].all() and not md[0, 0, :5].any()


def test_doc_remaining_and_anchor_sampling():
    seg = jnp.asarray([[0, 0, 0, 1, 1, 1, 1, 1]])
    rem = np.asarray(doc_remaining_from_segments(seg))
    np.testing.assert_array_equal(rem[0], [2, 1, 0, 4, 3, 2, 1, 0])

    cfg = TINY  # block_size 4 → anchor needs rem >= 3
    loss_mask = jnp.ones((1, 8), bool)
    anchors, keep = sample_anchors(jax.random.key(0), cfg, loss_mask, jnp.asarray(rem))
    a = sorted(np.asarray(anchors)[np.asarray(keep)])
    # only positions 3 and 4 keep the whole block inside document 1
    assert a == [3, 4]


@pytest.mark.slow
def test_block_loss_runs_and_vp_variant():
    rng = np.random.default_rng(0)
    B, S, A = 2, 32, 2
    ids = jnp.asarray(rng.integers(1, 128, (B, S), dtype=np.int32))
    ctx = jnp.asarray(rng.normal(size=(B, S, A * 32)).astype(np.float32))
    loss_mask = jnp.ones((B, S), bool)
    embed = jnp.asarray(rng.normal(size=(128, 32)).astype(np.float32) * 0.02)
    head = jnp.asarray(rng.normal(size=(32, 128)).astype(np.float32) * 0.02)
    params = init_drafter(TINY, jax.random.key(0))

    loss, m = dflash_block_loss(
        params, TINY, ids, ctx, loss_mask, jax.random.key(1), embed, head
    )
    assert np.isfinite(float(loss)) and float(loss) > 0
    assert float(m["valid_blocks"]) > 0
    assert 1.0 <= float(m["accept_length"]) <= TINY.block_size

    # gradient flows to the draft only (embed/head enter as frozen arrays)
    g = jax.grad(
        lambda p: dflash_block_loss(
            p, TINY, ids, ctx, loss_mask, jax.random.key(1), embed, head
        )[0]
    )(params)
    assert all(np.isfinite(np.asarray(x)).all() for x in jax.tree.leaves(g))
    assert any(float(jnp.abs(x).max()) > 0 for x in jax.tree.leaves(g))

    import dataclasses

    vp_cfg = dataclasses.replace(TINY, loss_type="variable_prefix")
    loss_vp, m_vp = dflash_block_loss(
        params, vp_cfg, ids, ctx, loss_mask, jax.random.key(1), embed, head
    )
    assert np.isfinite(float(loss_vp))
    # VP supervises fewer positions (visible prefixes are excluded)
    assert float(m_vp["valid_tokens"]) <= float(m["valid_tokens"])


def test_target_layer_ids():
    assert build_target_layer_ids(32, 1) == (16,)
    ids = build_target_layer_ids(32, 3)
    assert len(ids) == 3 and ids[0] == 1 and ids[-1] == 29


def test_export_roundtrip():
    params = init_drafter(TINY, jax.random.key(3))
    sd = drafter_to_hf(params, TINY)
    assert "model.fc.weight" in sd and "model.hidden_norm.weight" in sd
    assert "model.layers.1.self_attn.q_norm.weight" in sd
    assert not any("embed_tokens" in k or "lm_head" in k for k in sd)
    p2 = drafter_from_hf(lambda k: sd[k], TINY)
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(p2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))


TARGET_HF = {
    "architectures": ["LlamaForCausalLM"],
    "vocab_size": 128, "hidden_size": 32, "intermediate_size": 64,
    "num_hidden_layers": 4, "num_attention_heads": 4,
    "num_key_value_heads": 2,
}


@pytest.mark.recipe
def test_dflash_recipe_trains_and_exports(tmp_path):
    from automodel_tpu.cli.app import resolve_recipe_class
    from automodel_tpu.config import ConfigNode

    cfg = ConfigNode({
        "seed": 7,
        "run_dir": str(tmp_path),
        "auto_resume": False,
        "recipe": "llm_train_dflash",
        "target_model": {"hf_config": TARGET_HF, "dtype": "float32",
                         "remat_policy": "none"},
        "speculative": {"block_size": 4, "num_anchors": 8, "num_layers": 2,
                        "loss_decay_gamma": 2.0},
        "distributed": {"dp_shard": -1},
        "dataset": {
            "_target_": "automodel_tpu.datasets.mock.MockDatasetConfig",
            "num_samples": 32, "seq_len": 32, "vocab_size": 128,
        },
        "dataloader": {"microbatch_size": 8, "grad_acc_steps": 1},
        "optimizer": {"name": "adamw", "lr": 1e-3},
        "lr_scheduler": {"style": "constant", "warmup_steps": 0},
        "step_scheduler": {"max_steps": 3, "ckpt_every_steps": 100},
        "checkpoint": {"enabled": False},
    })
    r = resolve_recipe_class(cfg)(cfg)
    r.setup()
    r.run_train_validation_loop()
    recs = [json.loads(l) for l in open(tmp_path / "training.jsonl") if l.strip()]
    assert len(recs) == 3
    assert all(np.isfinite(x["loss"]) for x in recs)
    assert all("accept_length" in x for x in recs)

    out = r.save_consolidated_hf(str(tmp_path / "hf_draft"))
    cfg_json = json.loads(open(tmp_path / "hf_draft" / "config.json").read())
    assert cfg_json["dflash_config"]["target_layer_ids"]
    assert cfg_json["block_size"] == 4


@pytest.mark.slow
def test_dflash_decode_is_lossless():
    """Greedy speculative decoding commits EXACTLY the target's greedy
    continuation regardless of draft quality — the correctness property of
    the verify loop (a random draft just accepts less)."""
    from automodel_tpu.inference.generate import GenerateConfig, generate
    from automodel_tpu.models.registry import get_model_spec
    from automodel_tpu.speculative.decode_eval import dflash_decode

    spec = get_model_spec(TARGET_HF)
    tcfg = spec.config_from_hf(TARGET_HF, dtype=jnp.float32, remat_policy="none")
    tparams = spec.module.init(tcfg, jax.random.key(0))
    dcfg = TINY
    dparams = init_drafter(dcfg, jax.random.key(1))

    rng = np.random.default_rng(0)
    prompt = jnp.asarray(rng.integers(1, 128, (1, 8), dtype=np.int32))
    max_new = 12
    out, stats = dflash_decode(
        spec.module, tcfg, tparams, dparams, dcfg, (1, 2), prompt, max_new
    )
    ref = generate(
        tparams, tcfg, prompt, jax.random.key(0),
        GenerateConfig(max_new_tokens=max_new),
    )
    n = min(out.shape[1], ref.shape[1])
    np.testing.assert_array_equal(np.asarray(out[:, :n]), np.asarray(ref[:, :n]))
    assert stats["rounds"] >= 1
    assert stats["mean_accept_length"] >= 1.0
