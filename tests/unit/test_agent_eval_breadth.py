"""Breadth additions: agent/tool-call SFT dataset, NeAT knapsack packing,
validation-time sampling eval."""

import json

import jax
import numpy as np
import pytest


class _FakeTok:
    eos_token_id = 2
    chat_template = None

    def __call__(self, text, add_special_tokens=False):
        return {"input_ids": [ord(c) % 250 for c in text]}

    def encode(self, text, add_special_tokens=False):
        return self(text)["input_ids"]

    def decode(self, ids):
        return "".join(chr(i) for i in ids)


def test_agent_dataset_normalizes_sharegpt_tool_calls(tmp_path):
    from automodel_tpu.datasets.agent import (
        AgentChatDatasetConfig,
        normalize_agent_messages,
    )

    row = {
        "conversations": [
            {"from": "human", "value": "weather in SF?"},
            {"from": "function_call", "value": json.dumps(
                {"name": "get_weather", "arguments": {"city": "SF"}}
            )},
            {"from": "function_call", "value": json.dumps(
                {"name": "get_time", "arguments": {"tz": "PST"}}
            )},
            {"from": "observation", "value": "{\"temp\": 15}"},
            {"from": "gpt", "value": "It is 15C."},
        ],
        "tools": [{"name": "get_weather"}, {"name": "get_time"}],
    }
    msgs = normalize_agent_messages(row)
    assert msgs[0]["role"] == "system" and "get_weather" in msgs[0]["content"]
    assert msgs[1]["role"] == "user"
    # parallel calls merged into ONE assistant message with two blocks
    assert msgs[2]["role"] == "assistant"
    assert msgs[2]["content"].count("<tool_call>") == 2
    assert msgs[3]["role"] == "tool"
    assert msgs[4]["role"] == "assistant"

    # the serialized calls round-trip through the evaluator's parser
    from automodel_tpu.eval.tool_call_evaluator import parse_tool_calls

    calls = parse_tool_calls(msgs[2]["content"])
    assert [c["name"] for c in calls] == ["get_time", "get_weather"] or [
        c["name"] for c in calls
    ] == ["get_weather", "get_time"]

    # end-to-end through the dataset: only assistant tokens supervised
    p = tmp_path / "agent.jsonl"
    p.write_text(json.dumps(row) + "\n")
    ds = AgentChatDatasetConfig(path=str(p), seq_len=256).build(_FakeTok())
    ex = ds[0]
    assert (ex["labels"] != -100).sum() > 0


def test_knapsack_packing_tighter_than_first_fit():
    from automodel_tpu.datasets.packing import PackedSequenceConfig, pack_documents

    rng = np.random.default_rng(0)
    docs = [
        {"input_ids": np.ones(n, np.int32), "labels": np.ones(n, np.int32)}
        for n in rng.integers(10, 120, 64)
    ]
    ff = list(pack_documents(iter(docs), PackedSequenceConfig(seq_len=128)))
    ks = list(pack_documents(
        iter(docs), PackedSequenceConfig(seq_len=128, strategy="knapsack")
    ))
    # same tokens packed either way
    n_ff = sum(int((r["segment_ids"] > 0).sum()) for r in ff)
    n_ks = sum(int((r["segment_ids"] > 0).sum()) for r in ks)
    assert n_ff == n_ks
    assert len(ks) <= len(ff)  # knapsack never needs more rows
    # every row keeps per-document positions starting at 0
    for r in ks:
        segs = r["segment_ids"]
        for s in set(segs.tolist()) - {0}:
            pos = r["positions"][segs == s]
            assert pos[0] == 0 and (np.diff(pos) == 1).all()


@pytest.mark.recipe
def test_validation_generation_metrics(tmp_path):
    from automodel_tpu.cli.app import resolve_recipe_class
    from tests.unit.test_recipe import _smoke_cfg

    cfg = _smoke_cfg(tmp_path)
    cfg.set("checkpoint.enabled", False)
    cfg.set("step_scheduler.max_steps", 2)
    cfg.set("step_scheduler.val_every_steps", 2)
    cfg.set("validation_dataset", {
        "_target_": "automodel_tpu.datasets.mock.MockDatasetConfig",
        "num_samples": 16, "seq_len": 32, "vocab_size": 128,
    })
    cfg.set("validation_generation", {
        "prompt_len": 8, "max_new_tokens": 8, "max_batches": 1,
    })
    r = resolve_recipe_class(cfg)(cfg)
    r.setup()
    r.run_train_validation_loop()
    recs = [json.loads(l) for l in open(tmp_path / "validation.jsonl") if l.strip()]
    assert recs, "no validation records"
    assert "gen_token_accuracy" in recs[-1]
    assert 0.0 <= recs[-1]["gen_token_accuracy"] <= 1.0
    assert recs[-1]["gen_prefix_len"] >= 0.0
