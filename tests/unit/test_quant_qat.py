"""FP8-checkpoint dequant at load + QAT fake-quant training.

Reference anchors: models/deepseek_v3/state_dict_adapter.py:96 (block-wise
fp8 dequant of DSv3 checkpoints at load) and quantization/qat.py +
recipes/llm/train_ft.py:861 (torchao fake-quant with delayed enabling).
"""

import json
import struct

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from automodel_tpu.ops.quant import (
    QATConfig,
    fake_quantize,
    matmul,
    quantized_matmul,
)


def test_fp8_checkpoint_dequant_at_load(tmp_path):
    torch = pytest.importorskip("torch")
    from safetensors.torch import save_file

    from automodel_tpu.checkpoint.hf_adapter import HFCheckpointReader

    rng = np.random.default_rng(0)
    w = rng.normal(size=(160, 96)).astype(np.float32)  # not a multiple of 128
    scale_inv = rng.uniform(0.5, 2.0, size=(2, 1)).astype(np.float32)
    wq = torch.tensor(w).to(torch.float8_e4m3fn)
    save_file(
        {
            "model.layers.0.mlp.up_proj.weight": wq,
            "model.layers.0.mlp.up_proj.weight_scale_inv": torch.tensor(scale_inv),
            "model.norm.weight": torch.ones(96),
        },
        str(tmp_path / "model.safetensors"),
    )
    read = HFCheckpointReader(str(tmp_path))
    got = read("model.layers.0.mlp.up_proj.weight")
    assert got.dtype == np.float32
    # expected: fp8-rounded w times the block scale
    w8 = wq.to(torch.float32).numpy()
    exp = w8 * np.repeat(np.repeat(scale_inv, 128, 0), 128, 1)[:160, :96]
    np.testing.assert_allclose(got, exp, rtol=1e-6)
    # non-quantized tensors read unchanged
    np.testing.assert_array_equal(read("model.norm.weight"), np.ones(96, np.float32))


def test_fake_quantize_ste_gradient_and_grid():
    x = jnp.asarray(np.random.default_rng(1).normal(size=(8, 16)), jnp.float32)
    y = fake_quantize(x, "int8")
    # on the int8 grid: per-column scale, values land on multiples of it
    scale = np.abs(np.asarray(x)).max(0, keepdims=True) / 127.0 + 1e-12
    steps = np.asarray(y) / scale
    np.testing.assert_allclose(steps, np.round(steps), atol=1e-3)
    # STE: gradient of sum(fake_quantize(x)) is exactly ones
    g = jax.grad(lambda t: fake_quantize(t, "int8").sum())(x)
    np.testing.assert_array_equal(np.asarray(g), np.ones_like(x))


def test_qat_transform_delayed_enable_and_kernel_only():
    kernel = jnp.asarray(np.random.default_rng(2).normal(size=(4, 4)), jnp.float32)
    params = {
        "layers": {
            "q_proj": {"kernel": kernel, "bias": jnp.full((4,), 0.333, jnp.float32)},
            "norm": {"scale": jnp.full((4,), 0.333, jnp.float32)},
        }
    }
    tr = QATConfig(enabled=True, precision="int8", start_step=5).make_param_transform()
    before = tr(params, jnp.int32(0))
    after = tr(params, jnp.int32(5))
    # before start_step: identity
    np.testing.assert_array_equal(
        np.asarray(before["layers"]["q_proj"]["kernel"]), np.asarray(kernel)
    )
    # after: kernel snapped to the grid, bias/norm untouched
    k = np.asarray(after["layers"]["q_proj"]["kernel"])
    assert not np.allclose(k, np.asarray(kernel))
    np.testing.assert_array_equal(np.asarray(after["layers"]["q_proj"]["bias"]), np.full(4, 0.333, np.float32))
    np.testing.assert_array_equal(np.asarray(after["layers"]["norm"]["scale"]), np.full(4, 0.333, np.float32))
    assert QATConfig(enabled=False).make_param_transform() is None


@pytest.mark.slow
def test_train_step_with_qat_transform_trains():
    """A tiny regression under make_train_step with QAT on from step 0:
    loss must decrease and gradients must reach the master weights."""
    import optax

    from automodel_tpu.training import init_train_state, make_train_step

    rng = np.random.default_rng(3)
    X = jnp.asarray(rng.normal(size=(4, 8, 16)), jnp.float32)  # (accum, B, D)
    w_true = jnp.asarray(rng.normal(size=(16, 1)), jnp.float32)
    Y = jnp.einsum("abd,do->abo", X, w_true)

    def loss_fn(p, mb, rng_):
        pred = mb["x"] @ p["head"]["kernel"]
        return jnp.sum((pred - mb["y"]) ** 2), jnp.float32(mb["x"].shape[0])

    params = {"head": {"kernel": jnp.zeros((16, 1))}}
    tx = optax.sgd(5e-2)
    state = init_train_state(params, tx)
    step = make_train_step(
        loss_fn, tx,
        param_transform=QATConfig(enabled=True, precision="int8").make_param_transform(),
    )
    batch = {"x": X, "y": Y}
    losses = []
    for i in range(30):
        state, m = step(state, batch, jax.random.key(i))
        losses.append(float(m["loss"]))
    # int8 grid error floors the loss — expect substantial but not exact fit
    assert losses[-1] < 0.5 * losses[0]


def test_fp8_dequant_rejects_mismatched_scale_grid():
    from automodel_tpu.checkpoint.hf_adapter import _dequant_fp8_block

    w = np.zeros((160, 96), np.float32)
    with pytest.raises(ValueError, match="scale_inv grid"):
        _dequant_fp8_block(w, np.ones((3, 2), np.float32), (128, 128))
    # a [64, 64] block checkpoint works when the config says so
    out = _dequant_fp8_block(w + 1.0, 2.0 * np.ones((3, 2), np.float32), (64, 64))
    np.testing.assert_array_equal(out, np.full((160, 96), 2.0, np.float32))


@pytest.mark.slow
def test_qat_with_peft_raises():
    """QAT's kernel transform cannot see LoRA trees — the recipe must
    refuse the combination loudly instead of silently not quantizing."""
    from automodel_tpu.config.loader import ConfigNode
    from automodel_tpu.recipes.llm.train_ft import (
        TrainFinetuneRecipeForNextTokenPrediction,
    )

    cfg = ConfigNode({
        "run_dir": "/tmp/am_qat_peft",
        "model": {"hf_config": {
            "architectures": ["LlamaForCausalLM"], "vocab_size": 64,
            "hidden_size": 32, "intermediate_size": 64,
            "num_hidden_layers": 1, "num_attention_heads": 4,
            "num_key_value_heads": 2,
        }, "dtype": "float32", "remat_policy": "none"},
        "dataset": {
            "_target_": "automodel_tpu.datasets.mock.MockDatasetConfig",
            "num_samples": 8, "seq_len": 16, "vocab_size": 64,
        },
        "dataloader": {"microbatch_size": 2, "grad_acc_steps": 1},
        "step_scheduler": {"max_steps": 1},
        "checkpoint": {"enabled": False},
        "peft": {"r": 2},
        "qat": {"enabled": True},
    })
    r = TrainFinetuneRecipeForNextTokenPrediction(cfg)
    with pytest.raises(ValueError, match="does not compose with peft"):
        r.setup()


def test_quantize_clamps_nonfinite_before_cast():
    """`_quantize` must clamp in f32 BEFORE the low-precision cast: an inf
    input makes the amax scale inf, inf/inf = NaN, and float8_e4m3fn has
    no inf encoding so an unclamped cast of the overflow is NaN too — both
    quantized products must come back finite."""
    from automodel_tpu.ops.quant import FP8_MAX, _quantize

    x = jnp.asarray([[np.inf, 1.0, -3.0], [-np.inf, 2.0, 0.5]], jnp.float32)
    for precision, qdtype, qmax in (
        ("int8", jnp.int8, 127.0),
        ("fp8", jnp.float8_e4m3fn, FP8_MAX),
    ):
        q, scale = _quantize(x, qdtype, qmax, axis=-1)
        assert np.all(np.isfinite(np.asarray(scale))), precision
        assert np.all(np.isfinite(np.asarray(q, np.float32))), precision
        assert np.all(np.abs(np.asarray(q, np.float32)) <= qmax), precision


def test_quantize_near_fp8_max_saturates_not_nan():
    """Values straddling FP8_MAX (448): after per-axis rescale everything
    lands on the representable grid — saturation, never NaN — and the
    dequantized product stays close."""
    x = jnp.asarray([[447.9, 448.0, 448.1, -448.1, 1e30, -1e30]], jnp.float32)
    for precision in ("int8", "fp8"):
        got = quantized_matmul(x, jnp.eye(6, dtype=jnp.float32), precision)
        a = np.asarray(got, np.float32)
        assert np.all(np.isfinite(a)), (precision, a)
    # the finite near-max values survive quantization with small error
    small = jnp.asarray([[447.9, 400.0, -448.0, 100.0]], jnp.float32)
    got = quantized_matmul(small, jnp.eye(4, dtype=jnp.float32), "fp8")
    rel = np.abs(np.asarray(got) - np.asarray(small)) / np.abs(np.asarray(small))
    assert np.max(rel) < 0.1, rel


def test_kv_row_quant_roundtrip():
    """quantize_kv_rows/dequantize_kv: one f32 scale per leading-dim row,
    inf-safe, <1% relative error on the dominant row entries."""
    from automodel_tpu.ops.quant import dequantize_kv, quantize_kv_rows

    rng = np.random.default_rng(7)
    x = jnp.asarray(rng.normal(size=(6, 2, 16)) * 10.0, jnp.float32)
    q, scale = quantize_kv_rows(x)
    assert q.shape == x.shape and q.dtype == jnp.int8
    assert scale.shape == (6,) and scale.dtype == jnp.float32
    back = dequantize_kv(q, scale)
    err = np.abs(np.asarray(back - x))
    amax = np.abs(np.asarray(x)).max(axis=(1, 2), keepdims=True)
    assert np.max(err / amax) <= 0.5 / 127.0 + 1e-6
    # rows with inf quantize to finite saturated payloads
    bad = x.at[0, 0, 0].set(np.inf)
    qb, sb = quantize_kv_rows(bad)
    assert np.isfinite(float(sb[0]))
    assert np.all(np.isfinite(np.asarray(qb, np.float32)))


def test_quantized_matmul_per_channel_accuracy():
    """Per-channel scales keep error small when channels differ in scale
    by orders of magnitude (per-tensor scaling would destroy the small
    channel)."""
    rng = np.random.default_rng(4)
    x = jnp.asarray(rng.normal(size=(32, 64)), jnp.float32)
    w = np.asarray(rng.normal(size=(64, 2)), np.float32)
    w[:, 0] *= 1000.0
    w[:, 1] *= 0.001
    w = jnp.asarray(w)
    exact = x @ w
    got = quantized_matmul(x, w, "int8")
    rel = np.abs(np.asarray(got - exact)) / (np.abs(np.asarray(exact)) + 1e-9)
    assert np.median(rel[:, 0]) < 0.05 and np.median(rel[:, 1]) < 0.05
    # matmul dispatcher: None passes through exactly
    np.testing.assert_array_equal(np.asarray(matmul(x, w, None)), np.asarray(exact))
