"""EAGLE-3 speculative draft training tests.

Parity anchors: the TTT attention must reduce to plain causal attention at
step 0 (reference: draft_llama.py:312 — 'on the first call ... collapse to a
plain causal attention'), and simulated_accept_length must reproduce the
1 + Σ prefix-survival formula (reference: core.py:218)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

import pytest

pytestmark = pytest.mark.recipe

from automodel_tpu.speculative import (
    Eagle3Config,
    build_vocab_mapping,
    drafter_forward_step,
    drafter_param_specs,
    eagle3_ttt_loss,
    init_drafter,
    simulated_accept_length,
)

CFG = Eagle3Config(
    vocab_size=96,
    draft_vocab_size=48,
    hidden_size=32,
    intermediate_size=64,
    num_heads=4,
    num_kv_heads=2,
    ttt_steps=3,
)


def _inputs(B=2, T=12, seed=0):
    rng = np.random.default_rng(seed)
    ids = jnp.asarray(rng.integers(1, CFG.vocab_size, (B, T)), jnp.int32)
    aux = jnp.asarray(rng.normal(0, 1, (3, B, T, CFG.hidden_size)), jnp.float32)
    logits = jnp.asarray(rng.normal(0, 1, (B, T, CFG.vocab_size)), jnp.float32)
    mask = jnp.ones((B, T), bool)
    return ids, aux, logits, mask


def test_vocab_mapping():
    counts = jnp.asarray(np.arange(96, 0, -1), jnp.float32)
    d2t, t2d = build_vocab_mapping(counts, 48)
    assert d2t.shape == (48,) and t2d.shape == (96,)
    np.testing.assert_array_equal(np.asarray(d2t), np.arange(48))
    assert bool(t2d[0]) and not bool(t2d[95])
    # non-trivial counts: the top-k ids survive, sorted
    counts = jnp.zeros((96,)).at[jnp.asarray([5, 90, 17])].set(10.0)
    d2t, t2d = build_vocab_mapping(counts, 3)
    np.testing.assert_array_equal(np.asarray(d2t), [5, 17, 90])


def test_step0_attention_is_plain_causal():
    """With no cache, the fused layer's attention must equal standard causal
    attention over the same q/k/v — the TTT diagonals only appear later."""
    params = init_drafter(CFG, jax.random.key(0))
    ids, aux, _, _ = _inputs()
    B, T = ids.shape
    pos = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None], (B, T))
    hidden = jnp.moveaxis(aux, 0, -2).reshape(B, T, -1) @ params["fc"]["kernel"]

    h1, cache = drafter_forward_step(params, CFG, ids, hidden, pos, None, 0)
    assert np.isfinite(np.asarray(h1)).all()
    (k0, v0), (lk, lv) = cache
    # step 0's K/V becomes the causal block; no diagonal branches yet
    assert lk.shape[0] == 0 and k0.shape == (B, T, CFG.num_kv_heads, CFG.resolved_head_dim)

    # causality: changing a future token must not affect earlier outputs
    ids2 = ids.at[:, -1].set((ids[:, -1] + 1) % CFG.vocab_size)
    h2, _ = drafter_forward_step(params, CFG, ids2, hidden, pos, None, 0)
    np.testing.assert_allclose(
        np.asarray(h1[:, :-1]), np.asarray(h2[:, :-1]), rtol=1e-5, atol=1e-6
    )
    assert float(jnp.abs(h1[:, -1] - h2[:, -1]).max()) > 1e-6


def test_ttt_cache_grows_and_changes_output():
    params = init_drafter(CFG, jax.random.key(0))
    ids, aux, _, _ = _inputs()
    B, T = ids.shape
    pos = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None], (B, T))
    hidden = jnp.moveaxis(aux, 0, -2).reshape(B, T, -1) @ params["fc"]["kernel"]

    h, cache = drafter_forward_step(params, CFG, ids, hidden, pos, None, 0)
    h2_with, cache2 = drafter_forward_step(params, CFG, ids, h, pos, cache, 1)
    h2_wo, _ = drafter_forward_step(params, CFG, ids, h, pos, None, 1)
    assert cache2[1][0].shape[0] == 1  # step-1 K/V appended as a diagonal branch
    # the cached step-0 K/V branch must influence step 1
    assert float(jnp.abs(h2_with - h2_wo).max()) > 1e-6


def test_ttt_loss_grads_and_metrics():
    params = init_drafter(CFG, jax.random.key(1))
    ids, aux, logits, mask = _inputs()
    mask = mask.at[:, -2:].set(False)
    d2t, t2d = build_vocab_mapping(jnp.arange(96, 0, -1, dtype=jnp.float32), 48)

    def f(p):
        return eagle3_ttt_loss(p, CFG, ids, aux, logits, mask, d2t, t2d)

    (loss, m), g = jax.jit(jax.value_and_grad(f, has_aux=True))(params)
    assert np.isfinite(float(loss))
    # init loss ≈ CE against an (almost) random target restricted to Vd
    assert 2.0 < float(loss) < 8.0
    for leaf in jax.tree.leaves(g):
        assert np.isfinite(np.asarray(leaf)).all()
    assert m["step_prefix_hits"].shape == (CFG.ttt_steps,)
    # chain population shrinks as the shift rolls tokens out
    sv = np.asarray(m["step_valid"])
    assert (np.diff(sv) <= 0).all()
    assert 1.0 <= float(m["accept_length"]) <= 1.0 + CFG.ttt_steps


def test_simulated_accept_length_formula():
    hits = jnp.asarray([50, 20, 5])
    valid = jnp.asarray([100, 80, 50])
    expect = 1.0 + 50 / 100 + 20 / 80 + 5 / 50
    np.testing.assert_allclose(
        float(simulated_accept_length(hits, valid)), expect, rtol=1e-6
    )
    # zero-valid steps contribute nothing
    assert float(simulated_accept_length(jnp.zeros(3), jnp.zeros(3))) == 1.0


def test_perfect_target_drives_accept_length_up():
    """If the target distribution is exactly reproducible (peaked on tokens
    the drafter can fit), a few training steps must raise accept_length."""
    import optax

    cfg = dataclasses.replace(CFG, ttt_steps=2)
    params = init_drafter(cfg, jax.random.key(2))
    rng = np.random.default_rng(3)
    B, T = 4, 16
    ids = jnp.asarray(rng.integers(1, 48, (B, T)), jnp.int32)
    aux = jnp.asarray(rng.normal(0, 1, (3, B, T, cfg.hidden_size)), jnp.float32)
    # target: delta distribution on a fixed single token (easy to learn)
    tgt = jnp.full((B, T), 7, jnp.int32)
    logits = 20.0 * jax.nn.one_hot(tgt, cfg.vocab_size)
    mask = jnp.ones((B, T), bool)
    d2t, t2d = build_vocab_mapping(jnp.arange(96, 0, -1, dtype=jnp.float32), 48)

    tx = optax.adam(3e-3)
    opt = tx.init(params)

    @jax.jit
    def step(p, o):
        (l, m), g = jax.value_and_grad(
            lambda pp: eagle3_ttt_loss(pp, cfg, ids, aux, logits, mask, d2t, t2d),
            has_aux=True,
        )(p)
        u, o = tx.update(g, o, p)
        return optax.apply_updates(p, u), o, l, m

    params2, o, l0, m0 = step(params, opt)
    for _ in range(30):
        params2, o, l1, m1 = step(params2, o)
    assert float(l1) < float(l0)
    assert float(m1["accept_length"]) > float(m0["accept_length"])
    assert float(m1["accept_length"]) > 2.5  # near-perfect 2-step chain


def test_drafter_specs_match_params():
    params = init_drafter(CFG, jax.random.key(0))
    specs = drafter_param_specs(CFG)
    flat_p = jax.tree.leaves(params)
    flat_s = jax.tree.leaves(specs, is_leaf=lambda x: isinstance(x, tuple))
    assert len(flat_p) == len(flat_s)
    for p, s in zip(flat_p, flat_s):
        assert p.ndim == len(s), (p.shape, s)


def test_target_aux_hidden_capture_matches_prefix_runs():
    """decoder.forward(return_aux_hidden=...) must return exactly the
    per-layer outputs (pre-final-norm) at the selected indices."""
    from automodel_tpu.models.llm import decoder
    from automodel_tpu.models.llm.decoder import TransformerConfig

    tcfg = TransformerConfig(
        vocab_size=64, hidden_size=16, intermediate_size=32,
        num_layers=4, num_heads=2, num_kv_heads=1,
        dtype=jnp.float32, remat_policy="none",
    )
    params = decoder.init(tcfg, jax.random.key(0))
    ids = jnp.asarray(np.random.default_rng(0).integers(1, 64, (2, 8)), jnp.int32)
    logits, aux = decoder.forward(params, tcfg, ids, return_aux_hidden=(0, 2, 3))
    ref_logits = decoder.forward(params, tcfg, ids)
    np.testing.assert_allclose(np.asarray(logits), np.asarray(ref_logits), rtol=1e-6)

    # prefix truncation oracle: run only the first k+1 layers
    for j, lid in enumerate((0, 2, 3)):
        sub = dataclasses.replace(tcfg, num_layers=lid + 1)
        sub_params = dict(params)
        sub_params["layers"] = jax.tree.map(lambda x: x[: lid + 1], params["layers"])
        h_ref = decoder.forward(sub_params, sub, ids, return_hidden=True)
        # return_hidden applies the final norm; undo by comparing pre-norm:
        # capture includes no final norm, so compare via the capture of the
        # truncated model instead
        _, aux_sub = decoder.forward(sub_params, sub, ids, return_aux_hidden=(lid,))
        np.testing.assert_allclose(
            np.asarray(aux[j]), np.asarray(aux_sub[0]), rtol=1e-5, atol=1e-6
        )


def test_drafter_export_roundtrip(tmp_path):
    """SGLang-layout export → import reproduces params, d2t offsets, and the
    forward logits exactly (reference: draft_llama.py layout doc +
    set_vocab_mapping offset/mask conventions)."""
    from automodel_tpu.speculative.eagle3 import (
        drafter_from_hf,
        drafter_hf_config,
        drafter_to_hf,
    )

    params = init_drafter(CFG, jax.random.key(0))
    counts = jnp.arange(CFG.vocab_size, 0, -1, dtype=jnp.float32)
    d2t, t2d = build_vocab_mapping(counts, CFG.draft_vocab_size)

    sd = drafter_to_hf(params, CFG, d2t, t2d)
    assert sd["model.layers.0.self_attn.q_proj.weight"].shape == (
        CFG.num_heads * CFG.resolved_head_dim, 2 * CFG.hidden_size,
    )
    # offset convention: target_id = draft_id + d2t[draft_id]
    assert (np.asarray(sd["d2t"]) + np.arange(CFG.draft_vocab_size)).min() >= 0
    assert np.asarray(sd["t2d"]).sum() == CFG.draft_vocab_size

    # write + reread through the real safetensors writer
    from automodel_tpu.checkpoint.hf_adapter import save_hf_checkpoint

    out = str(tmp_path / "draft")
    save_hf_checkpoint(sd.items(), out, hf_config=drafter_hf_config(CFG))
    import json
    import os

    from safetensors.numpy import load_file

    files = [f for f in os.listdir(out) if f.endswith(".safetensors")]
    merged = {}
    for f in files:
        merged.update(load_file(os.path.join(out, f)))
    cfg_json = json.load(open(os.path.join(out, "config.json")))
    assert cfg_json["architectures"] == ["LlamaEagle3DraftModel"]

    params2, (d2t2, t2d2) = drafter_from_hf(lambda k: merged[k], CFG)
    np.testing.assert_array_equal(np.asarray(d2t2), np.asarray(d2t))
    np.testing.assert_array_equal(np.asarray(t2d2), np.asarray(t2d))
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(params2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=0, atol=0)


def test_moe_target_aux_hidden_capture():
    """MoE decoder aux-hidden capture: the last captured layer must equal the
    pre-final-norm hidden (final-norm of it == return_hidden output)."""
    from automodel_tpu.models.moe_lm import decoder as moe_decoder
    from automodel_tpu.models.moe_lm.decoder import MoETransformerConfig
    from automodel_tpu.moe.config import MoEConfig
    from automodel_tpu.ops.norms import rms_norm

    cfg = MoETransformerConfig(
        vocab_size=64, hidden_size=16, intermediate_size=32,
        num_layers=3, num_heads=2, num_kv_heads=1, first_k_dense=1,
        moe=MoEConfig(
            n_routed_experts=4, n_shared_experts=1, experts_per_token=2,
            moe_intermediate_size=8, shared_expert_intermediate_size=8,
        ),
        dtype=jnp.float32, remat_policy="none", attn_impl="xla",
    )
    params = moe_decoder.init(cfg, jax.random.key(0))
    ids = jnp.asarray(
        np.random.default_rng(0).integers(1, 64, (2, 8)), jnp.int32
    )
    (hidden, aux_h), _ = moe_decoder.forward(
        params, cfg, ids, return_hidden=True, return_aux_hidden=(0, 2)
    )
    assert aux_h.shape == (2, 2, 8, 16)
    renormed = rms_norm(
        aux_h[1], params["final_norm"]["scale"], cfg.rms_norm_eps,
        cfg.zero_centered_norm,
    )
    np.testing.assert_allclose(
        np.asarray(renormed), np.asarray(hidden), rtol=1e-5, atol=1e-5
    )
    # the two captures differ (layers actually ran in between)
    assert float(jnp.max(jnp.abs(aux_h[0] - aux_h[1]))) > 1e-3


def test_eagle1_loss_and_grads():
    """EAGLE-1/2: loss composition (hidden_w·SmoothL1 + token_w·softCE),
    finite grads, and the frozen head receiving no gradient."""
    from automodel_tpu.speculative.eagle1 import (
        Eagle1Config,
        drafter_param_specs as e1_specs,
        eagle1_loss,
        init_drafter as e1_init,
    )

    cfg = Eagle1Config(
        vocab_size=64, hidden_size=16, intermediate_size=32,
        num_heads=2, num_kv_heads=1, num_layers=2, feature_noise=0.1,
    )
    params = e1_init(cfg, jax.random.key(0))
    # specs cover the params tree exactly
    flat_p = jax.tree_util.tree_flatten_with_path(params)[0]
    flat_s = jax.tree_util.tree_flatten_with_path(
        e1_specs(cfg), is_leaf=lambda x: isinstance(x, tuple)
    )[0]
    assert {jax.tree_util.keystr(k) for k, _ in flat_p} == {
        jax.tree_util.keystr(k) for k, _ in flat_s
    }

    rng = np.random.default_rng(1)
    B, T, H, V = 2, 8, 16, 64
    ids = jnp.asarray(rng.integers(1, V, (B, T)), jnp.int32)
    hid = jnp.asarray(rng.normal(size=(B, T, H)), jnp.float32)
    tgt_hid = jnp.asarray(rng.normal(size=(B, T, H)), jnp.float32)
    logits = jnp.asarray(rng.normal(size=(B, T, V)), jnp.float32)
    head = jnp.asarray(rng.normal(size=(H, V)), jnp.float32)
    mask = jnp.ones((B, T), bool).at[:, -1].set(False)

    def f(p, head):
        loss, m = eagle1_loss(
            p, cfg, ids, hid, tgt_hid, logits, head, mask,
            rng=jax.random.key(0),
        )
        return loss, m

    (loss, m), grads = jax.value_and_grad(f, has_aux=True, argnums=0)(params, head)
    assert np.isfinite(float(loss))
    expected = (
        cfg.hidden_loss_weight * float(m["hidden_loss"])
        + cfg.token_loss_weight * float(m["token_loss"])
    )
    np.testing.assert_allclose(float(loss), expected, rtol=1e-6)
    assert all(np.isfinite(np.asarray(g)).all() for g in jax.tree.leaves(grads))
    # frozen head: grad wrt head must be zero (stop_gradient inside)
    g_head = jax.grad(lambda h: f(params, h)[0])(head)
    np.testing.assert_allclose(np.asarray(g_head), 0.0, atol=0)
