"""Launcher manifest-generation tests (reference: slurm.sub, components/
launcher/* — here the launcher GENERATES one-process-per-host job specs;
jax.distributed handles rendezvous, no torchrun re-exec)."""

import os
import subprocess
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest
import yaml

from automodel_tpu.launcher import (
    LauncherConfig,
    render_gke_jobset,
    render_slurm_script,
)


def test_slurm_script_fields():
    cfg = LauncherConfig(
        backend="slurm", nodes=8, job_name="ft8", account="acct",
        partition="tpu", time_limit="02:00:00",
    )
    s = render_slurm_script(cfg, "examples/llm_finetune/tiny_llama_mock_smoke.yaml")
    assert s.startswith("#!/bin/bash")
    assert "#SBATCH -N 8" in s
    assert "#SBATCH --ntasks-per-node=1" in s
    assert "#SBATCH -A acct" in s and "#SBATCH -p tpu" in s
    # rank comes from SLURM_PROCID, read directly by distributed/init_utils
    assert "JAX_COORDINATOR_ADDRESS" in s and "JAX_NUM_PROCESSES" in s
    assert "python -m automodel_tpu examples/llm_finetune/tiny_llama_mock_smoke.yaml" in s
    assert "--signal=B:USR1@300" in s  # checkpoint-then-exit grace


def test_gke_jobset_is_valid_yaml_with_tpu_resources():
    cfg = LauncherConfig(
        backend="gke", nodes=4, job_name="pretrain", tpu_type="tpu-v5p-slice",
        tpu_topology="2x2x4", tpu_chips_per_host=4, image="my/image:1",
    )
    doc = yaml.safe_load(render_gke_jobset(cfg, "cfg.yaml"))
    assert doc["kind"] == "JobSet"
    job = doc["spec"]["replicatedJobs"][0]["template"]["spec"]
    assert job["parallelism"] == 4 and job["completions"] == 4
    pod = job["template"]["spec"]
    sel = pod["nodeSelector"]
    assert sel["cloud.google.com/gke-tpu-accelerator"] == "tpu-v5p-slice"
    assert sel["cloud.google.com/gke-tpu-topology"] == "2x2x4"
    c = pod["containers"][0]
    assert c["resources"]["limits"]["google.com/tpu"] == 4
    assert "python -m automodel_tpu cfg.yaml" in c["args"][0]
    # preempted pods must be restartable: backoffLimit 0 turned every TPU
    # spot reclaim into a dead job even though the recipe auto-resumes from
    # its emergency checkpoint — the default is a small bounded budget
    assert job["backoffLimit"] == 3
    doc2 = yaml.safe_load(
        render_gke_jobset(
            LauncherConfig(backend="gke", backoff_limit=7), "cfg.yaml"
        )
    )
    assert doc2["spec"]["replicatedJobs"][0]["template"]["spec"]["backoffLimit"] == 7


def test_launcher_rejects_bad_backend():
    with pytest.raises(ValueError, match="slurm|gke"):
        LauncherConfig(backend="torchrun")
    with pytest.raises(ValueError, match="backoff_limit"):
        LauncherConfig(backend="gke", backoff_limit=-1)


def test_cli_launch_writes_spec(tmp_path):
    out = subprocess.run(
        [sys.executable, "-m", "automodel_tpu", "launch",
         "examples/llm_finetune/tiny_llama_mock_smoke.yaml",
         "--launcher.backend=gke", "--launcher.nodes=2",
         f"--launcher.output_dir={tmp_path}", "--launcher.job_name=smoke"],
        capture_output=True, text=True, timeout=120, cwd=REPO_ROOT,
    )
    assert out.returncode == 0, out.stderr[-800:]
    spec = (tmp_path / "smoke.yaml").read_text()
    assert yaml.safe_load(spec)["metadata"]["name"] == "smoke"
