"""KD loss/recipe + Muon optimizer + training utils."""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.recipe

from automodel_tpu.loss.kd_loss import fused_kd_cross_entropy, soft_cross_entropy_sum
from automodel_tpu.loss.masked_ce import IGNORE_INDEX, cross_entropy_sum


def test_soft_ce_limits():
    rng = np.random.default_rng(0)
    s = jnp.asarray(rng.normal(size=(2, 6, 16)), jnp.float32)
    labels = jnp.asarray(rng.integers(0, 16, (2, 6)))
    # teacher == one-hot labels at T=1 → soft CE == hard CE
    t = jax.nn.one_hot(labels, 16) * 1e4
    soft, n = soft_cross_entropy_sum(s, t, labels)
    hard, n2 = cross_entropy_sum(s, labels)
    assert n == n2
    np.testing.assert_allclose(float(soft), float(hard), rtol=1e-4)
    # masked tokens contribute nothing
    labels2 = labels.at[0, :3].set(IGNORE_INDEX)
    soft2, n3 = soft_cross_entropy_sum(s, t, labels2)
    assert n3 == n - 3 and float(soft2) < float(soft)


def test_fused_kd_matches_unfused():
    rng = np.random.default_rng(1)
    B, S, H, V = 2, 8, 12, 24
    sh = jnp.asarray(rng.normal(size=(B, S, H)), jnp.float32)
    th = jnp.asarray(rng.normal(size=(B, S, H)), jnp.float32)
    sk = jnp.asarray(rng.normal(size=(H, V)), jnp.float32)
    tk = jnp.asarray(rng.normal(size=(H, V)), jnp.float32)
    labels = jnp.asarray(rng.integers(0, V, (B, S)))

    total, n = fused_kd_cross_entropy(
        sh, sk, th, tk, labels, kd_ratio=0.3, temperature=2.0, chunk_size=4
    )
    hard, _ = cross_entropy_sum(sh @ sk, labels)
    soft, _ = soft_cross_entropy_sum(sh @ sk, th @ tk, labels, temperature=2.0)
    np.testing.assert_allclose(
        float(total), 0.7 * float(hard) + 0.3 * float(soft), rtol=1e-4
    )


def test_kd_recipe_trains(tmp_path):
    from tests.unit.test_recipe import _smoke_cfg
    from automodel_tpu.cli.app import resolve_recipe_class

    cfg = _smoke_cfg(tmp_path, recipe="llm_kd")
    cfg.set("teacher_model", {
        "hf_config": {
            "architectures": ["LlamaForCausalLM"],
            "vocab_size": 128, "hidden_size": 48, "intermediate_size": 96,
            "num_hidden_layers": 2, "num_attention_heads": 4,
            "num_key_value_heads": 2,
        },
        "dtype": "float32",
    })
    cfg.set("kd", {"ratio": 0.5, "temperature": 2.0})
    recipe_cls = resolve_recipe_class(cfg)
    assert recipe_cls.__name__ == "KDRecipeForNextTokenPrediction"
    r = recipe_cls(cfg)
    r.setup()
    r.run_train_validation_loop()
    recs = [json.loads(l) for l in open(tmp_path / "training.jsonl")]
    assert len(recs) == 4 and all(np.isfinite(x["loss"]) for x in recs)


def test_muon_orthogonalizes_and_trains():
    from automodel_tpu.optim.muon import MuonConfig, _newton_schulz

    g = jnp.asarray(np.random.default_rng(2).normal(size=(16, 8)), jnp.float32)
    o = _newton_schulz(g, steps=10)
    # Muon's quintic NS is intentionally loose: singular values compress to
    # ~[0.6, 1.3] (vs g's wide spread), directions preserved
    sg = np.linalg.svd(np.asarray(g), compute_uv=False)
    so = np.linalg.svd(np.asarray(o), compute_uv=False)
    assert sg.max() / sg.min() > 3
    assert so.min() > 0.5 and so.max() < 1.4, so

    # end-to-end: tiny decoder trains under muon
    from automodel_tpu.models.llm import decoder
    from automodel_tpu.models.llm.decoder import TransformerConfig
    from automodel_tpu.loss import fused_linear_cross_entropy
    from automodel_tpu.optim import OptimizerConfig
    from automodel_tpu.training import init_train_state, make_train_step

    cfg = TransformerConfig(
        vocab_size=64, hidden_size=32, intermediate_size=48, num_layers=2,
        num_heads=4, num_kv_heads=2, dtype=jnp.float32, remat_policy="none",
    )
    params = decoder.init(cfg, jax.random.key(0))
    tx = OptimizerConfig(name="muon", lr=0.02, weight_decay=0.0).build()
    state = init_train_state(params, tx)

    def loss_fn(p, b, rng):
        h = decoder.forward(p, cfg, b["input_ids"], return_hidden=True)
        return fused_linear_cross_entropy(h, p["lm_head"]["kernel"], b["labels"], chunk_size=32)

    step = jax.jit(make_train_step(loss_fn, tx), donate_argnums=0)
    ids = jax.random.randint(jax.random.key(1), (1, 4, 17), 0, 64)
    batch = {"input_ids": ids[..., :-1], "labels": ids[..., 1:]}
    losses = []
    for i in range(20):
        state, m = step(state, batch, jax.random.key(i))
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0] * 0.7, losses


def test_ema_and_neftune_and_timers():
    import time

    from automodel_tpu.training.utils import (
        Timers,
        init_ema,
        neftune_noise,
        update_ema,
    )

    params = {"w": jnp.ones((4,))}
    ema = init_ema(params)
    new = {"w": jnp.zeros((4,))}
    ema = update_ema(ema, new, 0.9)
    np.testing.assert_allclose(np.asarray(ema["w"]), 0.9)

    e = jnp.zeros((2, 8, 16))
    noised = neftune_noise(e, jax.random.key(0), alpha=5.0)
    mag = 5.0 / np.sqrt(8 * 16)
    assert 0 < float(jnp.abs(noised).max()) <= mag

    t = Timers()
    with t("x"):
        time.sleep(0.01)
    s = t.summary()
    assert s["x"]["count"] == 1 and s["x"]["total_s"] >= 0.01


@pytest.mark.parametrize("precision", ["fp8", "int8"])
def test_quantized_matmul_close_and_trainable(precision):
    from automodel_tpu.ops.quant import quantized_matmul

    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.normal(size=(8, 32)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(32, 16)), jnp.float32)
    out = quantized_matmul(x, w, precision)
    ref = x @ w
    rel = float(jnp.linalg.norm(out - ref) / jnp.linalg.norm(ref))
    assert rel < (0.05 if precision == "fp8" else 0.02), rel
    # grads flow (bf16 backward)
    g = jax.grad(lambda x, w: jnp.sum(quantized_matmul(x, w, precision) ** 2), argnums=(0, 1))(x, w)
    gr = jax.grad(lambda x, w: jnp.sum((x @ w) ** 2), argnums=(0, 1))(x, w)
    for a, b in zip(g, gr):
        rel = float(jnp.linalg.norm(a - b) / jnp.linalg.norm(b))
        assert rel < 0.1, rel


def test_fp8_decoder_forward():
    from automodel_tpu.models.llm import decoder
    from automodel_tpu.models.llm.decoder import TransformerConfig

    cfg = TransformerConfig(
        vocab_size=64, hidden_size=32, intermediate_size=48, num_layers=2,
        num_heads=4, num_kv_heads=2, dtype=jnp.float32, remat_policy="none",
        linear_precision="fp8",
    )
    params = decoder.init(cfg, jax.random.key(0))
    out = decoder.forward(params, cfg, jnp.zeros((1, 8), jnp.int32))
    assert np.isfinite(np.asarray(out)).all()


def test_dion_optimizes_and_is_low_rank():
    """Dion (arXiv:2504.05295 Alg. 1): loss decreases on a matrix-factor
    problem, Q state stays (n, rank), error-feedback momentum is finite."""
    import optax

    from automodel_tpu.optim.dion import scale_by_dion

    rng = np.random.default_rng(0)
    W_true = jnp.asarray(rng.normal(0, 1, (32, 16)), jnp.float32)
    params = {"layer": {"kernel": jnp.zeros((32, 16))},
              "bias": jnp.zeros((16,))}

    tx = optax.chain(scale_by_dion(rank=8), optax.scale(-0.1))
    # Dion handles matrices; give the 1-D leaf to adamw via multi_transform
    from automodel_tpu.optim.muon import matrix_param_labeler

    tx = optax.multi_transform(
        {"matrix": tx, "adamw": optax.adam(0.1)},
        lambda p: matrix_param_labeler(p, "matrix")
    )
    opt = tx.init(params)

    def loss(p):
        return jnp.mean((p["layer"]["kernel"] - W_true) ** 2) + jnp.mean(p["bias"] ** 2)

    l0 = float(loss(params))
    for _ in range(120):
        g = jax.grad(loss)(params)
        u, opt = tx.update(g, opt, params)
        params = optax.apply_updates(params, u)
    assert float(loss(params)) < 0.2 * l0
    q = opt.inner_states["matrix"].inner_state[0].q["layer"]["kernel"]
    assert q.shape == (16, 8)


def test_dion_via_optimizer_config():
    from automodel_tpu.optim import OptimizerConfig

    tx = OptimizerConfig(name="dion", lr=1e-2, dion_rank=8).build()
    params = {"w": jnp.ones((8, 8)), "embed": {"embedding": jnp.ones((4, 8))}}
    state = tx.init(params)
    g = jax.tree.map(jnp.ones_like, params)
    u, _ = tx.update(g, state, params)
    assert jax.tree.leaves(u)[0].shape is not None


def test_param_group_overrides():
    """`optimizer.param_groups` — per-pattern lr_mult / weight_decay
    (reference: optim/optimizer.py param-group machinery)."""
    from automodel_tpu.optim import OptimizerConfig

    params = {"embed": {"embedding": jnp.ones((4, 8))}, "w": jnp.ones((8, 8))}
    g = jax.tree.map(jnp.ones_like, params)

    base = OptimizerConfig(name="adamw", lr=1e-1, weight_decay=0.0)
    tx0 = base.build()
    u0, _ = tx0.update(g, tx0.init(params), params)

    cfg = OptimizerConfig(
        name="adamw", lr=1e-1, weight_decay=0.0,
        param_groups=({"pattern": "embed", "lr_mult": 0.0},),
    )
    tx1 = cfg.build()
    u1, _ = tx1.update(g, tx1.init(params), params)
    # embed group frozen (lr_mult 0), other params unchanged vs baseline
    assert float(jnp.abs(u1["embed"]["embedding"]).max()) == 0.0
    np.testing.assert_allclose(np.asarray(u1["w"]), np.asarray(u0["w"]), rtol=1e-6)


def test_dora_identity_at_init_and_magnitude_grads():
    """DoRA (arXiv:2402.09353): with b=0 the merged weights equal the base
    exactly (m = ||W||_col, v/||v|| restores direction); magnitude params
    receive gradients."""
    from automodel_tpu.peft.lora import LoRAConfig, init_lora, merge_lora

    cfg = LoRAConfig(r=4, dora=True, target_modules=("w",))
    base = {"w": {"kernel": jnp.asarray(
        np.random.default_rng(0).normal(0, 1, (16, 8)), jnp.float32)}}
    lora = init_lora(base, cfg, jax.random.key(0))
    assert "m" in lora["w/kernel"]
    merged = merge_lora(base, lora, cfg)
    np.testing.assert_allclose(
        np.asarray(merged["w"]["kernel"]), np.asarray(base["w"]["kernel"]),
        rtol=1e-5, atol=1e-6,
    )

    def loss(lo):
        m = merge_lora(base, lo, cfg)
        return jnp.sum(m["w"]["kernel"] ** 2)

    g = jax.grad(loss)(lora)
    assert float(jnp.abs(g["w/kernel"]["m"]).max()) > 0
    # at init dL/da is proportional to b == 0; b receives signal first
    assert float(jnp.abs(g["w/kernel"]["b"]).max()) > 0


def test_qlora_int8_base():
    """QLoRA: int8 base storage dequantizes inside merge within absmax
    quantization error; adapters train on top."""
    from automodel_tpu.peft.lora import (
        LoRAConfig, init_lora, merge_lora, quantize_base,
    )

    cfg = LoRAConfig(r=4, quantize_base="int8", target_modules=("w",))
    rng = np.random.default_rng(1)
    base = {"w": {"kernel": jnp.asarray(rng.normal(0, 0.1, (32, 16)), jnp.float32)},
            "norm": {"scale": jnp.ones((16,))}}
    lora = init_lora(base, cfg, jax.random.key(0))
    qbase = quantize_base(base, cfg)
    assert qbase["w"]["kernel"]["q8"].dtype == jnp.int8
    assert qbase["norm"]["scale"].dtype == jnp.float32  # 1-D untouched

    merged = merge_lora(qbase, lora, cfg)
    err = np.abs(np.asarray(merged["w"]["kernel"]) - np.asarray(base["w"]["kernel"]))
    # absmax-per-channel int8: error bounded by scale/2 per channel
    bound = np.abs(np.asarray(base["w"]["kernel"])).max(0) / 127.0
    assert (err <= bound[None, :] + 1e-7).all()

    def loss(lo):
        m = merge_lora(qbase, lo, cfg)
        return jnp.sum(m["w"]["kernel"] ** 2)

    g = jax.grad(loss)(lora)
    assert np.isfinite(np.asarray(g["w/kernel"]["a"])).all()
