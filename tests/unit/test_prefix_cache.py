"""Prefix cache: radix matching, COW, eviction, and engine-level parity.

The acceptance contract of the prefix-sharing layer:

- greedy outputs with the cache ENABLED are token-for-token identical to a
  cold (cache-disabled) engine on overlapping ragged streams — including
  divergence mid-page (copy-on-write), a preempted-and-requeued request
  whose prefix is shared, and defrag firing while pages are multiply
  referenced;
- the jitted step keeps ONE compiled signature across hit / miss / COW
  steps (the fixed-shape contract survives the new subsystem untouched);
- the radix hit actually skips prefill (> 50% of prompt tokens on a
  shared-system-prompt stream — the bench `prefix` headline's workload).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from automodel_tpu.models.llm import decoder
from automodel_tpu.models.llm.decoder import TransformerConfig
from automodel_tpu.serving import (
    PageAllocator,
    PrefixCache,
    PrefixCacheConfig,
    Request,
    Scheduler,
    ServingConfig,
    ServingEngine,
)

CFG = TransformerConfig(
    vocab_size=64, hidden_size=32, intermediate_size=48, num_layers=2,
    num_heads=4, num_kv_heads=2, qk_norm=True, dtype=jnp.float32,
    remat_policy="none",
)
ENABLED = PrefixCacheConfig(enabled=True)


# -- radix tree unit tests ----------------------------------------------------
def _tree(num_pages=16, ps=4, **kw):
    alloc = PageAllocator(num_pages=num_pages, page_size=ps)
    return alloc, PrefixCache(alloc, ps, PrefixCacheConfig(enabled=True, **kw))


def _fill(alloc, slot, n_tokens):
    alloc.ensure(slot, n_tokens)
    return list(alloc.table(slot))


def test_radix_match_is_page_granular():
    alloc, tree = _tree()
    toks = list(range(1, 11))            # 10 tokens, ps=4 → 2 full pages
    pages = _fill(alloc, 0, 10)
    assert tree.insert(toks, pages[:2]) == 2
    assert tree.cached_pages == 2

    # exact full-page prefix: both pages, fed to the divergence point
    m = tree.lookup(toks[:8] + [99, 98])
    assert m.pages == pages[:2] and m.fed == 8 and not m.cow_pending

    # divergence INSIDE page 2 → only page 1 matches fully
    m = tree.lookup(toks[:5] + [99, 98, 97])
    assert m.pages[0] == pages[0] and m.fed >= 4

    # full hit on an exact page multiple: capped one token short → COW
    m = tree.lookup(toks[:8])
    assert m.pages == pages[:2] and m.fed == 7 and m.cow_pending

    # no overlap at all
    m = tree.lookup([50, 51, 52, 53, 54])
    assert m.pages == [] and m.fed == 0


def test_radix_partial_page_match_sets_cow():
    """Mid-page divergence with share_partial: the divergent page is
    adopted by longest-common-prefix and flagged for copy-on-write."""
    alloc, tree = _tree()
    toks = list(range(1, 9))
    pages = _fill(alloc, 0, 8)
    tree.insert(toks, pages[:2])
    m = tree.lookup(toks[:6] + [99, 98])  # diverges 2 tokens into page 2
    assert m.pages == pages[:2] and m.fed == 6 and m.cow_pending

    alloc2, tree2 = _tree(share_partial=False)
    pages2 = _fill(alloc2, 0, 8)
    tree2.insert(toks, pages2[:2])
    m2 = tree2.lookup(toks[:6] + [99, 98])
    assert m2.pages == pages2[:1] and m2.fed == 4 and not m2.cow_pending


def test_radix_insert_dedupes_and_caps():
    alloc, tree = _tree(max_pages=2)
    toks = list(range(1, 13))
    pages = _fill(alloc, 0, 12)
    assert tree.insert(toks, pages[:3]) == 2    # capacity stops the third
    assert tree.insert(toks, pages[:3]) == 0    # pure dedupe
    assert tree.cached_pages == 2


def test_lru_reclaim_frees_coldest_unreferenced_first():
    alloc, tree = _tree(num_pages=8)
    a = _fill(alloc, 0, 4)
    b = _fill(alloc, 1, 4)
    tree.insert([1, 2, 3, 4], a)
    tree.insert([9, 8, 7, 6], b)
    alloc.free_slot(0)
    alloc.free_slot(1)                  # both pages now tree-only
    tree.lookup([9, 8, 7, 6, 5])        # touch b → a is the LRU victim
    assert tree.reclaimable() == 2
    assert tree.reclaim(1) == 1
    assert tree.cached_pages == 1
    assert alloc.num_free == 7          # a's page went back to the pool
    m = tree.lookup([9, 8, 7, 6, 5])
    assert m.pages == b                 # survivor is the recently used one


def test_reclaim_skips_pages_pinned_by_slots():
    alloc, tree = _tree(num_pages=8)
    a = _fill(alloc, 0, 4)
    tree.insert([1, 2, 3, 4], a)        # refcount 2: slot 0 + tree
    assert tree.reclaimable() == 0
    assert tree.reclaim(4) == 0         # nothing evictable while pinned
    alloc.free_slot(0)
    assert tree.reclaimable() == 1 and tree.reclaim(4) == 1


def test_tree_follows_defrag_remap():
    alloc, tree = _tree(num_pages=8)
    _fill(alloc, 0, 8)                  # slot 0: pages 0, 1
    b = _fill(alloc, 1, 8)              # slot 1: pages 2, 3
    toks = [1, 2, 3, 4, 5, 6, 7, 8]
    tree.insert(toks, b)                # pin pages 2, 3
    alloc.free_slot(0)                  # holes at 0, 1
    alloc.free_slot(1)                  # pages 2, 3 are tree-only now
    plan = alloc.defrag_plan()
    assert plan is not None
    m = tree.lookup(toks + [9])
    assert m.pages == [0, 1] and m.fed == 8  # nodes follow the compaction


# -- engine-level parity (the satellite contract) -----------------------------
def _ragged(seed, lens, vocab=64):
    rng = np.random.default_rng(seed)
    return [[int(t) for t in rng.integers(1, vocab, (l,))] for l in lens]


def _serve(params, serve_cfg, prompts, arrivals, max_new=6):
    engine = ServingEngine(params, CFG, serve_cfg)
    reqs = [Request(prompt=list(p), max_new_tokens=max_new, arrival=a)
            for p, a in zip(prompts, arrivals)]
    return engine.serve_batch(reqs)


def test_warm_vs_cold_parity_overlapping_stream():
    """Token-for-token greedy parity vs the cache-disabled engine on a
    stream of overlapping prompts: full hits (page-aligned AND not),
    divergence mid-page (COW), and cold misses, concurrent and staggered."""
    params = decoder.init(CFG, jax.random.key(0))
    (sys_p,) = _ragged(1, [9])
    prompts = [
        sys_p + t for t in _ragged(2, [3, 5, 2])  # shared system prompt
    ] + [
        sys_p[:8],                     # page-aligned full hit → COW
        sys_p + [5],                   # full hit one past the shared prefix
        sys_p[:6] + [61, 62, 63],      # diverges mid-page → partial COW
        _ragged(3, [7])[0],            # cold miss
    ]
    arrivals = [0, 3, 5, 7, 9, 11, 13]
    geo = dict(page_size=4, num_pages=32, max_slots=3, pages_per_slot=8,
               token_budget=8, prefill_chunk=4)
    cold = _serve(params, ServingConfig(**geo), prompts, arrivals)
    warm = _serve(params, ServingConfig(**geo, prefix_cache=ENABLED),
                  prompts, arrivals)
    assert warm["outputs"] == cold["outputs"]
    stats = warm["stats"]
    assert stats["prefix_hits"] >= 4
    assert stats["prefill_skipped_tokens"] >= 20
    assert stats["cow_copies"] >= 2
    assert stats["compiled_signatures"] == 1
    # the cache saved real prefill work: fewer tokens through the device
    assert stats["tokens_fed"] < cold["stats"]["tokens_fed"]


def test_preempted_request_readmits_through_its_own_donation():
    """Preempt-and-requeue with the cache on: the victim's donated pages
    turn its recompute-style re-prefill into a radix hit, and outputs still
    match the cold engine exactly."""
    params = decoder.init(CFG, jax.random.key(0))
    prompts = _ragged(20, [4, 4, 4])
    geo = dict(page_size=2, num_pages=10, max_slots=3, pages_per_slot=6,
               token_budget=6, prefill_chunk=3)
    cold = _serve(params, ServingConfig(**geo), prompts, [0, 0, 0], max_new=5)
    warm = _serve(params, ServingConfig(**geo, prefix_cache=ENABLED),
                  prompts, [0, 0, 0], max_new=5)
    assert warm["outputs"] == cold["outputs"]
    assert warm["stats"]["compiled_signatures"] == 1
    assert cold["stats"]["preemptions"] >= 1
    if warm["stats"]["preemptions"]:   # victim re-admitted via the tree
        assert warm["stats"]["prefix_hits"] >= 1


def test_defrag_with_multiply_referenced_pages_preserves_decode():
    """Force compaction while shared pages are live in several tables AND
    the radix tree: every output still matches the cold engine."""
    params = decoder.init(CFG, jax.random.key(0))
    (sys_p,) = _ragged(30, [8])
    prompts = [sys_p + t for t in _ragged(31, [2, 3, 4])]
    # 8+2+5 = 15 tokens: request 0's last page stays partial, so finishing
    # frees it (donated full pages survive) and punches a mid-pool hole
    # while requests 1/2 still share the system-prompt pages
    geo = dict(page_size=4, num_pages=24, max_slots=3, pages_per_slot=6,
               token_budget=8, prefill_chunk=4)
    cold = _serve(params, ServingConfig(**geo), prompts, [0, 1, 2], max_new=5)

    engine = ServingEngine(params, CFG, ServingConfig(
        **geo, prefix_cache=ENABLED,
    ))
    sched = engine.make_scheduler()
    for i, p in enumerate(prompts):
        sched.submit(Request(prompt=list(p), max_new_tokens=5, arrival=i))
    step = 0
    defrags = 0
    while sched.has_work:
        plan = sched.schedule(step)
        if plan is not None:
            tokens, _ = engine.run_step(plan)
            sched.update(plan, tokens, step)
            shared = any(
                sched.alloc.refcount(p) > 1
                for t in sched.alloc._tables.values() for p in t
            )
            if shared and engine.defrag(sched):
                defrags += 1
        step += 1
    assert defrags >= 1, "defrag never fired while pages were shared"
    outs = [r.generated for r in sorted(sched.finished, key=lambda r: r.rid)]
    assert outs == cold["outputs"]
    assert engine.step_cache_size() == 1


def test_full_hit_goes_straight_to_decode():
    """A resubmitted identical prompt skips prefill entirely: its only fed
    rows before sampling are decode-class (one pending token)."""
    params = decoder.init(CFG, jax.random.key(0))
    (p,) = _ragged(40, [8])
    engine = ServingEngine(params, CFG, ServingConfig(
        page_size=4, num_pages=16, max_slots=2, pages_per_slot=4,
        token_budget=8, prefix_cache=ENABLED,
    ))
    sched = engine.make_scheduler()
    sched.submit(Request(prompt=list(p), max_new_tokens=4))
    sched.submit(Request(prompt=list(p), max_new_tokens=4, arrival=4))
    first_feed = {}
    step = 0
    while sched.has_work:
        plan = sched.schedule(step)
        if plan is not None:
            for slot, c, _ in plan.scheduled:
                rid = sched.running[slot].rid
                first_feed.setdefault(rid, c)
            tokens, _ = engine.run_step(plan)
            sched.update(plan, tokens, step)
        step += 1
    a, b = sorted(sched.finished, key=lambda r: r.rid)
    assert b.generated == a.generated
    assert first_feed[0] == 8        # cold prefill of the whole prompt
    assert first_feed[1] == 1        # full hit: first step is the decode row
    assert b.prefix_hit_tokens == 7
    assert sched.n_cow >= 1          # page-aligned hit splits the last page


def test_prefix_hit_admission_policy_prefers_hits_when_tight():
    """Non-FIFO admission: with the pool too tight for the cold queue head,
    the high-hit-ratio waiter behind it is admitted first; FIFO order
    resumes once pages free up, and nothing is lost or reordered wrongly."""
    params = decoder.init(CFG, jax.random.key(0))
    (sys_p,) = _ragged(50, [16])            # 4 full pages of system prompt
    hot = sys_p + _ragged(51, [2])[0]       # needs 1 fresh page after the hit
    cold_long = _ragged(52, [16])[0]        # needs 5 pages, no hit
    engine = ServingEngine(params, CFG, ServingConfig(
        page_size=4, num_pages=9, max_slots=2, pages_per_slot=6,
        token_budget=16, prefill_chunk=16,
        prefix_cache=ENABLED, admission_policy="prefix-hit",
    ))
    sched = engine.make_scheduler()
    warmer = Request(prompt=list(sys_p) + [9], max_new_tokens=4)
    sched.submit(warmer)                      # seeds the tree, hogs pages
    sched.submit(Request(prompt=list(cold_long), max_new_tokens=4, arrival=2))
    sched.submit(Request(prompt=list(hot), max_new_tokens=4, arrival=2))
    admit_order = []
    step = 0
    while sched.has_work and step < 200:
        plan = sched.schedule(step)
        if plan is not None:
            for slot, req in sched.running.items():
                if req.rid not in admit_order:
                    admit_order.append(req.rid)
            tokens, _ = engine.run_step(plan)
            sched.update(plan, tokens, step)
        step += 1
    assert not sched.has_work
    assert admit_order.index(2) < admit_order.index(1), (
        f"hit-ratio waiter was not preferred: {admit_order}"
    )
    assert len(sched.finished) == 3


def test_shared_system_prompt_skips_majority_of_prefill():
    """The bench `prefix` headline's workload shape in miniature: an
    agent-loop stream re-sending its whole history must skip > 50% of
    prompt tokens (the acceptance bar for the headline)."""
    params = decoder.init(CFG, jax.random.key(0))
    (sys_p,) = _ragged(60, [12])
    turns = _ragged(61, [4, 4, 4])
    prompts, hist = [], list(sys_p)
    for t in turns:                     # history grows every round
        hist = hist + t
        prompts.append(list(hist))
    arrivals = [6 * i for i in range(len(prompts))]
    res = _serve(
        params,
        ServingConfig(page_size=4, num_pages=48, max_slots=3,
                      pages_per_slot=12, token_budget=8, prefill_chunk=8,
                      prefix_cache=ENABLED),
        prompts, arrivals, max_new=4,
    )
    total_prompt = sum(len(p) for p in prompts)
    skipped = res["stats"]["prefill_skipped_tokens"]
    assert skipped / total_prompt > 0.5, (skipped, total_prompt)
    assert res["stats"]["compiled_signatures"] == 1


def test_eviction_capped_cache_still_parity():
    """A tiny max_pages forces constant LRU eviction; parity must hold."""
    params = decoder.init(CFG, jax.random.key(0))
    (sys_p,) = _ragged(70, [8])
    prompts = [sys_p + t for t in _ragged(71, [3, 4, 5])]
    geo = dict(page_size=4, num_pages=24, max_slots=2, pages_per_slot=6,
               token_budget=8, prefill_chunk=4)
    cold = _serve(params, ServingConfig(**geo), prompts, [0, 2, 4])
    warm = _serve(
        params,
        ServingConfig(**geo, prefix_cache=PrefixCacheConfig(
            enabled=True, max_pages=3,
        )),
        prompts, [0, 2, 4],
    )
    assert warm["outputs"] == cold["outputs"]
    assert warm["stats"]["prefix_cached_pages"] <= 3


def test_admission_accounting_excludes_pages_the_request_would_pin():
    """Regression: admission must not count a candidate's own matched
    tree-only pages as BOTH adopted (subtracted from need) and reclaimable
    (added to avail) — adoption pins them. Pool = 3; the donor leaves 2
    tree-only pages + 1 free. An identical page-aligned prompt needs a COW
    page + a decode-slack page on top of the 2 it would pin: the honest
    ledger says that does not fit (1 free + 0 reclaimable-after-pinning),
    so the admit must fall back to COLD — reclaiming the tree during
    prefill — instead of leaning on preemption/reclaim it already spent.
    A roomier pool takes the warm hit; outputs match either way."""
    params = decoder.init(CFG, jax.random.key(0))
    (donor_prompt,) = _ragged(80, [8])   # exactly 2 pages of known tokens

    def run(num_pages):
        engine = ServingEngine(params, CFG, ServingConfig(
            page_size=4, num_pages=num_pages, max_slots=2, pages_per_slot=3,
            token_budget=8, prefix_cache=ENABLED,
        ))
        return engine.serve_batch([
            Request(prompt=list(donor_prompt), max_new_tokens=0),
            Request(prompt=list(donor_prompt), max_new_tokens=1, arrival=4),
        ])

    res = run(num_pages=3)               # tight: warm admit must be refused
    assert [r.finish_reason for r in res["requests"]] == ["length", "length"]
    assert res["stats"]["prefix_hits"] == 0           # cold admission
    assert res["stats"]["prefix_evicted_pages"] >= 1  # tree reclaimed
    res2 = run(num_pages=8)              # roomy: the hit goes through
    assert res2["stats"]["prefix_hits"] == 1
    assert res2["outputs"] == res["outputs"]


def test_config_validation():
    with pytest.raises(ValueError):
        PrefixCacheConfig(enabled=True, eviction="random")
    with pytest.raises(ValueError):
        Scheduler(num_pages=8, page_size=2, max_slots=1, pages_per_slot=4,
                  token_budget=4, admission_policy="prefix-hit")
