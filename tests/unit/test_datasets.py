"""Dataset tier: mock, packing, column-mapped, native index helpers, token-bin."""

import numpy as np
import pytest

from automodel_tpu.datasets.megatron.index_helpers import (
    _load,
    build_blending_indices,
    build_sample_index,
    build_shuffle_index,
)
from automodel_tpu.datasets.megatron.gpt_dataset import TokenBinDatasetConfig
from automodel_tpu.datasets.mock import MockDatasetConfig
from automodel_tpu.datasets.packing import PackedSequenceConfig, pack_documents


def test_native_lib_compiles():
    assert _load() is not None, "g++ build of index_helpers.cpp failed"


def test_sample_index_contiguous():
    doc_lens = np.asarray([5, 3, 7], np.int32)  # 15 tokens
    idx = build_sample_index(doc_lens, seq_len=4, num_samples=3)
    # each sample consumes 5 tokens (seq+1): boundaries at 0,5,10,15
    assert idx.shape == (4, 2)
    np.testing.assert_array_equal(idx[0], [0, 0])
    np.testing.assert_array_equal(idx[1], [1, 0])   # 5 tokens = doc0 exactly
    np.testing.assert_array_equal(idx[2], [2, 2])   # next 5: doc1(3)+doc2[:2]
    np.testing.assert_array_equal(idx[3], [3, 0])   # exhausts doc2


def test_sample_index_matches_numpy_fallback():
    rng = np.random.default_rng(0)
    doc_lens = rng.integers(1, 50, 200).astype(np.int32)
    native = build_sample_index(doc_lens, 16, 100)
    import automodel_tpu.datasets.megatron.index_helpers as ih

    saved, ih._lib, ih._tried = ih._lib, None, True  # force fallback
    try:
        fallback = build_sample_index(doc_lens, 16, 100)
    finally:
        ih._lib, ih._tried = saved, True
    np.testing.assert_array_equal(native, fallback)


def test_shuffle_index_is_permutation_and_deterministic():
    a = build_shuffle_index(1000, seed=7)
    b = build_shuffle_index(1000, seed=7)
    c = build_shuffle_index(1000, seed=8)
    np.testing.assert_array_equal(a, b)
    assert not np.array_equal(a, c)
    assert sorted(a.tolist()) == list(range(1000))


def test_blending_tracks_weights():
    w = np.asarray([0.7, 0.2, 0.1])
    ds_idx, ds_sample = build_blending_indices(w, 1000)
    counts = np.bincount(ds_idx, minlength=3)
    np.testing.assert_allclose(counts / 1000, w, atol=0.01)
    # within-dataset sample indices are sequential
    for d in range(3):
        np.testing.assert_array_equal(
            ds_sample[ds_idx == d], np.arange(counts[d])
        )


def test_token_bin_dataset(tmp_path):
    tokens = np.arange(1000, dtype=np.uint16) % 97
    tokens.tofile(tmp_path / "corpus.bin")
    doc_lens = np.asarray([300, 200, 500], np.int32)
    np.save(tmp_path / "corpus.doclens.npy", doc_lens)
    ds = TokenBinDatasetConfig(prefix=str(tmp_path / "corpus"), seq_len=64, seed=1).build()
    assert len(ds) == (1000 - 1) // 64
    s = ds[0]
    assert s["input_ids"].shape == (64,)
    # labels are inputs shifted by one
    np.testing.assert_array_equal(s["input_ids"][1:], s["labels"][:-1])
    # deterministic across instances; different across epochs
    ds2 = TokenBinDatasetConfig(prefix=str(tmp_path / "corpus"), seq_len=64, seed=1).build()
    np.testing.assert_array_equal(ds[3]["input_ids"], ds2[3]["input_ids"])
    ds2.set_epoch(1)
    assert any(
        not np.array_equal(ds[i]["input_ids"], ds2[i]["input_ids"])
        for i in range(len(ds))
    )


def test_packing_round_trip():
    docs = [
        {"input_ids": np.arange(5), "labels": np.arange(5) + 1},
        {"input_ids": np.arange(3), "labels": np.arange(3) + 1},
        {"input_ids": np.arange(6), "labels": np.arange(6) + 1},
    ]
    rows = list(pack_documents(docs, PackedSequenceConfig(seq_len=8, pad_id=0)))
    assert len(rows) == 2
    r0 = rows[0]
    np.testing.assert_array_equal(r0["segment_ids"][:8], [1] * 5 + [2] * 3)
    np.testing.assert_array_equal(r0["positions"][:5], np.arange(5))
    r1 = rows[1]
    assert (r1["segment_ids"][:6] == 1).all() and (r1["segment_ids"][6:] == 0).all()
    assert (r1["labels"][6:] == -100).all()


def test_mock_packed_has_boundaries():
    ds = MockDatasetConfig(num_samples=4, seq_len=64, vocab_size=100, packed=True).build()
    s = ds[0]
    assert "segment_ids" in s and "positions" in s
    assert s["segment_ids"].max() >= 1
    # positions restart at document boundaries
    jumps = np.flatnonzero(np.diff(s["segment_ids"]))
    assert (s["positions"][jumps + 1] == 0).all()


def test_packing_capacity_align():
    """align=S/cp: no document crosses an align boundary (the blockdiag CP
    contract); over-align docs are truncated to one sub-buffer."""
    import numpy as np

    from automodel_tpu.datasets.packing import PackedSequenceConfig, pack_documents

    docs = [
        {"input_ids": np.arange(1, 13), "labels": np.arange(1, 13)},   # 12
        {"input_ids": np.arange(1, 9), "labels": np.arange(1, 9)},     # 8
        {"input_ids": np.arange(1, 25), "labels": np.arange(1, 25)},   # 24 > align
        {"input_ids": np.arange(1, 6), "labels": np.arange(1, 6)},     # 5
    ]
    rows = list(pack_documents(docs, PackedSequenceConfig(seq_len=32, align=16)))
    for row in rows:
        seg = row["segment_ids"]
        for d in set(seg[seg > 0]):
            idx = np.nonzero(seg == d)[0]
            assert idx[0] // 16 == idx[-1] // 16, (d, idx)   # one sub-buffer
            assert len(idx) <= 16
    # every document appears; the 24-doc is truncated to one sub-buffer (16)
    lengths = sorted(
        int((row["segment_ids"] == d).sum())
        for row in rows
        for d in set(row["segment_ids"][row["segment_ids"] > 0])
    )
    assert lengths == [5, 8, 12, 16]
