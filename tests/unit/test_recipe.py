"""End-to-end recipe smoke tests (the CI recipe-test tier analog,
reference: tests/ci_tests/ — mock datasets, per-step JSONL assertions)."""

import json
import os

import jax
import numpy as np
import pytest

pytestmark = pytest.mark.recipe

from automodel_tpu.cli.app import main, resolve_recipe_class
from automodel_tpu.config import ConfigNode


def _smoke_cfg(tmp_path, **over):
    cfg = {
        "seed": 7,
        "run_dir": str(tmp_path),
        "auto_resume": True,
        "model": {
            "hf_config": {
                "architectures": ["LlamaForCausalLM"],
                "vocab_size": 128, "hidden_size": 32, "intermediate_size": 64,
                "num_hidden_layers": 2, "num_attention_heads": 4,
                "num_key_value_heads": 2,
            },
            "dtype": "float32",
            "remat_policy": "none",
        },
        "distributed": {"dp_shard": -1},
        "dataset": {
            "_target_": "automodel_tpu.datasets.mock.MockDatasetConfig",
            "num_samples": 128, "seq_len": 32, "vocab_size": 128,
        },
        "dataloader": {"microbatch_size": 8, "grad_acc_steps": 2},
        "optimizer": {"name": "adamw", "lr": 1e-3, "weight_decay": 0.0},
        "lr_scheduler": {"warmup_steps": 1, "decay_steps": 10, "style": "cosine"},
        "step_scheduler": {"max_steps": 4, "ckpt_every_steps": 2, "num_epochs": 2},
        "checkpoint": {
            "enabled": True,
            "checkpoint_dir": str(tmp_path / "ckpt"),
            "async_save": False,
        },
        "loss": {"chunk_size": 32},
    }
    node = ConfigNode(cfg)
    for k, v in over.items():
        node.set(k, v)
    return node


def test_recipe_train_checkpoints_and_metrics(tmp_path):
    recipe_cls = resolve_recipe_class(_smoke_cfg(tmp_path))
    recipe = recipe_cls(_smoke_cfg(tmp_path))
    recipe.setup()
    recipe.run_train_validation_loop()

    records = [
        json.loads(l) for l in open(tmp_path / "training.jsonl") if l.strip()
    ]
    assert [r["step"] for r in records] == [1, 2, 3, 4]
    for r in records:
        assert np.isfinite(r["loss"]) and np.isfinite(r["grad_norm"])
        assert "tps" in r and "mfu_pct" in r
    assert sorted(
        int(d) for d in os.listdir(tmp_path / "ckpt") if d.isdigit()
    ) == [2, 4]


def test_recipe_resume_continues_steps(tmp_path):
    recipe_cls = resolve_recipe_class(_smoke_cfg(tmp_path))
    r1 = recipe_cls(_smoke_cfg(tmp_path))
    r1.setup()
    r1.run_train_validation_loop()

    r2 = recipe_cls(_smoke_cfg(tmp_path, **{"step_scheduler.max_steps": 6}))
    r2.setup()
    assert r2.step_scheduler.step == 4  # resumed
    assert int(r2.train_state.step) == 4
    r2.run_train_validation_loop()
    records = [
        json.loads(l) for l in open(tmp_path / "training.jsonl") if l.strip()
    ]
    assert records[-1]["step"] == 6


def test_recipe_consolidated_hf_export(tmp_path):
    cfg = _smoke_cfg(tmp_path, **{"checkpoint.save_consolidated": True})
    recipe = resolve_recipe_class(cfg)(cfg)
    recipe.setup()
    recipe.run_train_validation_loop()
    hf_dir = tmp_path / "ckpt" / "hf"
    assert (hf_dir / "model.safetensors").exists()
    assert (hf_dir / "config.json").exists()

    # reload the export as a pretrained_path → same params
    cfg2 = _smoke_cfg(tmp_path / "second")
    cfg2.set("model.pretrained_path", str(hf_dir))
    cfg2.set("checkpoint.enabled", False)
    cfg2.set("auto_resume", False)
    r2 = resolve_recipe_class(cfg2)(cfg2)
    r2.setup()
    a = jax.tree.leaves(recipe.train_state.params)
    b = jax.tree.leaves(r2.train_state.params)
    for x, y in zip(a, b):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), rtol=1e-6)


def test_recipe_moe_smoke(tmp_path):
    cfg = _smoke_cfg(tmp_path)
    cfg.set("model.hf_config", {
        "architectures": ["Qwen3MoeForCausalLM"],
        "vocab_size": 128, "hidden_size": 32, "intermediate_size": 64,
        "num_hidden_layers": 2, "num_attention_heads": 4,
        "num_key_value_heads": 2, "num_experts": 4, "num_experts_per_tok": 2,
        "moe_intermediate_size": 16, "router_aux_loss_coef": 0.01,
    })
    cfg.set("distributed", {"dp_shard": -1, "ep": 2})
    recipe = resolve_recipe_class(cfg)(cfg)
    recipe.setup()
    recipe.run_train_validation_loop()
    records = [
        json.loads(l) for l in open(tmp_path / "training.jsonl") if l.strip()
    ]
    assert len(records) == 4
    assert all("moe_load_imbalance" in r for r in records)


def _run_and_read_losses(cfg):
    recipe = resolve_recipe_class(cfg)(cfg)
    recipe.setup()
    recipe.run_train_validation_loop()
    run_dir = cfg.get("run_dir")
    records = [
        json.loads(l) for l in open(os.path.join(run_dir, "training.jsonl"))
        if l.strip()
    ]
    return recipe, [r["loss"] for r in records]


def test_recipe_cp_load_balanced_parity(tmp_path):
    """The load-balanced CP layout is a pure relabeling: attention is
    position-causal (ring) and CE is per-token, so the permuted run must
    reproduce the unpermuted losses exactly (VERDICT r3 weak #2)."""
    losses = {}
    for lb in (True, False):
        cfg = _smoke_cfg(
            tmp_path / f"lb_{lb}",
            **{
                "step_scheduler.max_steps": 3,
                "checkpoint.enabled": False,
                "auto_resume": False,
            },
        )
        cfg.set("distributed", {"dp_shard": 4, "cp": 2, "cp_load_balanced": lb})
        recipe, losses[lb] = _run_and_read_losses(cfg)
        assert (recipe.cp_sharder is not None) == lb
    np.testing.assert_allclose(losses[True], losses[False], rtol=2e-5, atol=2e-6)


def test_recipe_pipeline_1f1b_from_config(tmp_path):
    """`distributed.pipeline_schedule: 1f1b` routes training through the
    explicit 1F1B interleave; its losses must match the GPipe+autodiff
    schedule step for step (VERDICT r3 weak #3 — 1F1B was dead code)."""
    losses = {}
    for sched in ("gpipe", "1f1b"):
        cfg = _smoke_cfg(
            tmp_path / sched,
            **{
                "step_scheduler.max_steps": 3,
                "checkpoint.enabled": False,
                "auto_resume": False,
            },
        )
        cfg.set("distributed", {
            "pp": 2, "dp_shard": 4,
            "pipeline_schedule": sched, "pipeline_microbatches": 2,
        })
        _, losses[sched] = _run_and_read_losses(cfg)
    np.testing.assert_allclose(losses["1f1b"], losses["gpipe"], rtol=1e-4, atol=1e-5)


def test_recipe_restore_from_explicit_dir(tmp_path):
    cfg1 = _smoke_cfg(tmp_path / "a")
    r1 = resolve_recipe_class(cfg1)(cfg1)
    r1.setup()
    r1.run_train_validation_loop()

    cfg2 = _smoke_cfg(tmp_path / "b")
    cfg2.set("checkpoint.restore_from", str(tmp_path / "a" / "ckpt"))
    r2 = resolve_recipe_class(cfg2)(cfg2)
    r2.setup()
    assert int(r2.train_state.step) == 4
    a = jax.tree.leaves(r1.train_state.params)
    b = jax.tree.leaves(r2.train_state.params)
    for x, y in zip(a, b):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y))


def test_benchmark_recipe_alias(tmp_path):
    cfg = _smoke_cfg(tmp_path, recipe="llm_benchmark")
    cfg.set("benchmark.warmup_steps", 1)
    recipe_cls = resolve_recipe_class(cfg)
    assert recipe_cls.__name__ == "BenchmarkRecipe"
    r = recipe_cls(cfg)
    r.setup()
    r.run_train_validation_loop()
    import json as _json

    recs = [_json.loads(l) for l in open(tmp_path / "training.jsonl")]
    assert recs[-1]["metric"] == "benchmark_step_seconds"


def test_dataloader_mid_epoch_resume_no_replay(tmp_path):
    from automodel_tpu.datasets.loader import DataloaderConfig
    from automodel_tpu.datasets.mock import MockDatasetConfig

    ds = MockDatasetConfig(num_samples=32, seq_len=8, vocab_size=64).build()
    dl = DataloaderConfig(microbatch_size=4, shuffle=False).build(ds)
    it = iter(dl)
    first = next(it)["input_ids"]
    state = dl.state_dict()
    assert state == {"epoch": 0, "batch_index": 1}

    dl2 = DataloaderConfig(microbatch_size=4, shuffle=False).build(ds)
    dl2.load_state_dict(state)
    dl2.set_epoch(0)  # what StepScheduler does on resume — must NOT rewind
    second = next(iter(dl2))["input_ids"]
    assert not np.array_equal(first, second)


def test_recipe_lora_peft(tmp_path):
    cfg = _smoke_cfg(tmp_path)
    cfg.set("peft", {"r": 4, "alpha": 8.0, "target_modules": ["q_proj", "v_proj"]})
    recipe = resolve_recipe_class(cfg)(cfg)
    recipe.setup()
    # trainable = lora only; base frozen outside optimizer
    n_train = sum(p.size for p in jax.tree.leaves(recipe.train_state.params))
    n_base = sum(p.size for p in jax.tree.leaves(recipe.base_params))
    assert n_train < n_base / 10
    base_before = jax.tree.map(lambda x: np.asarray(x).copy(), recipe.base_params)
    recipe.run_train_validation_loop()
    # base untouched, adapters moved
    for a, b in zip(jax.tree.leaves(base_before), jax.tree.leaves(recipe.base_params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    b_leaves = [
        v["b"] for v in recipe.train_state.params.values()
    ]
    assert any(float(np.abs(np.asarray(b)).sum()) > 0 for b in b_leaves)
    import json as _json

    recs = [_json.loads(l) for l in open(tmp_path / "training.jsonl")]
    assert recs[-1]["step"] == 4 and np.isfinite(recs[-1]["loss"])


def test_benchmark_recipe_moe_fake_gate(tmp_path):
    cfg = _smoke_cfg(tmp_path, recipe="llm_benchmark")
    cfg.set("model.hf_config", {
        "architectures": ["Qwen3MoeForCausalLM"],
        "vocab_size": 128, "hidden_size": 32, "intermediate_size": 64,
        "num_hidden_layers": 2, "num_attention_heads": 4,
        "num_key_value_heads": 2, "num_experts": 4, "num_experts_per_tok": 2,
        "moe_intermediate_size": 16,
    })
    cfg.set("benchmark.warmup_steps", 1)
    r = resolve_recipe_class(cfg)(cfg)
    r.setup()
    assert r.model_cfg.moe.fake_balanced_gate  # benchmark conditions active
    r.run_train_validation_loop()
    import json as _json

    recs = [_json.loads(l) for l in open(tmp_path / "training.jsonl")]
    assert recs[-1]["metric"] == "benchmark_step_seconds"


def test_profiling_trace_capture(tmp_path):
    cfg = _smoke_cfg(tmp_path)
    cfg.set("profiling", {"trace_dir": str(tmp_path / "trace"), "start_step": 1, "num_steps": 2})
    r = resolve_recipe_class(cfg)(cfg)
    r.setup()
    r.run_train_validation_loop()
    assert r.profiler.done
    import glob

    assert glob.glob(str(tmp_path / "trace" / "**" / "*.pb"), recursive=True) or glob.glob(
        str(tmp_path / "trace" / "**" / "*.json.gz"), recursive=True
    ), "no trace files written"


@pytest.mark.slow  # 1f1b-from-config covers the explicit-schedule recipe wiring in tier-1
def test_recipe_pipeline_interleaved_from_config(tmp_path):
    """`distributed.pipeline_schedule: interleaved` (virtual-stage 1F1B)
    matches gpipe losses step for step."""
    losses = {}
    for sched in ("gpipe", "interleaved"):
        cfg = _smoke_cfg(
            tmp_path / sched,
            **{
                "step_scheduler.max_steps": 3,
                "checkpoint.enabled": False,
                "auto_resume": False,
            },
        )
        cfg.set("model.hf_config.num_hidden_layers", 4)
        cfg.set("distributed", {
            "pp": 2, "dp_shard": 4,
            "pipeline_schedule": sched, "pipeline_microbatches": 2,
            "pipeline_virtual_stages": 2,
        })
        _, losses[sched] = _run_and_read_losses(cfg)
    np.testing.assert_allclose(
        losses["interleaved"], losses["gpipe"], rtol=1e-4, atol=1e-5
    )


@pytest.mark.slow  # ~20s compile; unit grad-parity (test_pp_moe) guards tier-1
def test_recipe_pipeline_moe_pp_ep_from_config(tmp_path):
    """The flagship PP×EP composition from config: MoE under the explicit
    1F1B and ZB schedules (fence lifted, ISSUE 1) matches the gpipe step
    losses. pp=2 puts BOTH paths on the pipelined MoE forward, so the
    per-chunk aux estimator is identical across schedules."""
    losses = {}
    for sched in ("gpipe", "1f1b", "zb"):
        cfg = _smoke_cfg(
            tmp_path / sched,
            **{
                "step_scheduler.max_steps": 3,
                "checkpoint.enabled": False,
                "auto_resume": False,
            },
        )
        cfg.set("model.hf_config", {
            "architectures": ["Qwen3MoeForCausalLM"],
            "vocab_size": 128, "hidden_size": 32, "intermediate_size": 64,
            "num_hidden_layers": 2, "num_attention_heads": 4,
            "num_key_value_heads": 2, "num_experts": 4,
            "num_experts_per_tok": 2, "moe_intermediate_size": 16,
            "router_aux_loss_coef": 0.01,
        })
        # pinned routing: cross-schedule loss parity needs routing-stable
        # programs (live top-k flips near-ties on compile-level fp noise)
        cfg.set("model.fake_balanced_gate", True)
        cfg.set("distributed", {
            "pp": 2, "ep": 2, "dp_shard": 2,
            "pipeline_schedule": sched, "pipeline_microbatches": 2,
        })
        _, losses[sched] = _run_and_read_losses(cfg)
    np.testing.assert_allclose(losses["1f1b"], losses["gpipe"], rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(losses["zb"], losses["gpipe"], rtol=1e-4, atol=1e-5)


@pytest.mark.slow  # ~20s compile; unit grad-parity (test_pp_moe) guards tier-1
def test_recipe_pipeline_peft_1f1b_from_config(tmp_path):
    """PEFT × explicit 1F1B (the merge-vjp composition in _make_grad_fn)
    matches PEFT × gpipe losses; base weights stay frozen."""
    losses = {}
    for sched in ("gpipe", "1f1b"):
        cfg = _smoke_cfg(
            tmp_path / sched,
            **{
                "step_scheduler.max_steps": 3,
                "checkpoint.enabled": False,
                "auto_resume": False,
            },
        )
        cfg.set("peft", {"r": 4, "alpha": 8.0, "target_modules": ["q_proj", "v_proj"]})
        cfg.set("distributed", {
            "pp": 2, "dp_shard": 4,
            "pipeline_schedule": sched, "pipeline_microbatches": 2,
        })
        recipe, losses[sched] = _run_and_read_losses(cfg)
        n_train = sum(p.size for p in jax.tree.leaves(recipe.train_state.params))
        n_base = sum(p.size for p in jax.tree.leaves(recipe.base_params))
        assert n_train < n_base / 10
    np.testing.assert_allclose(losses["1f1b"], losses["gpipe"], rtol=1e-4, atol=1e-5)
