"""The static-analysis gate: lint rule fixtures, allowlist semantics,
HLO report parsing, the baseline ratchet, and the CI entry point.

Each lint rule gets a fixture snippet with a KNOWN violation asserting
rule ID + line span + suppression behavior — the "deliberately introduced
violation of each kind fails it" half of the acceptance criteria. The
baseline half is a synthetic-drift test (mutate one count, the ratchet
fires) — the real five entry points are compared in test_hlo_guards.py.
Finally, the gate itself runs in-process on the package: the lint prong
must exit 0 (clean modulo the justified allowlist)."""

import textwrap

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from automodel_tpu.analysis.hlo import HLOReport, analyze_compiled, compare_report
from automodel_tpu.analysis.lint import (
    AllowlistError,
    apply_allowlist,
    lint_source,
    load_allowlist,
)


def _rules(findings):
    return [f.rule for f in findings]


# -- rule fixtures: one known violation per rule ------------------------------


def test_am101_item_in_jit_body():
    src = textwrap.dedent("""
        import jax

        @jax.jit
        def fwd(x):
            y = x * 2
            return y.item()
    """)
    fs = lint_source(src)
    assert _rules(fs) == ["AM101"]
    assert fs[0].token == "item"
    assert fs[0].line == 7  # the `return y.item()` line (1-based, after \\n)
    assert fs[0].qualname == "fwd"


def test_am101_np_asarray_reachable_through_helper():
    """Reachability crosses plain calls: the hazard sits in a helper the
    jitted body calls, not in the jit root itself."""
    src = textwrap.dedent("""
        import jax
        import numpy as np

        def helper(x):
            return np.asarray(x)

        @jax.jit
        def fwd(x):
            return helper(x) + 1
    """)
    fs = lint_source(src)
    assert _rules(fs) == ["AM101"]
    assert fs[0].token == "np.asarray"
    assert fs[0].qualname == "helper"


def test_am101_float_cast_of_param():
    src = textwrap.dedent("""
        import jax

        @jax.jit
        def fwd(x):
            return float(x) + 1.0
    """)
    fs = lint_source(src)
    assert _rules(fs) == ["AM101"] and fs[0].token == "float"


def test_am101_shape_and_static_config_casts_are_clean():
    """float(x.shape[-1]) is static metadata; int(cfg.k) follows the
    static-config convention; params declared static_argnames are exempt."""
    src = textwrap.dedent("""
        import jax
        from functools import partial

        @partial(jax.jit, static_argnames=("n",))
        def fwd(x, cfg, n):
            scale = float(x.shape[-1]) ** -0.5
            k = int(cfg.top_k) + int(n)
            return x * scale + k
    """)
    assert lint_source(src) == []


def test_am102_clock_and_rng_in_jit():
    src = textwrap.dedent("""
        import jax
        import random
        import time
        import numpy as np

        @jax.jit
        def fwd(x):
            t = time.time()
            r = random.random()
            z = np.random.uniform()
            return x + t + r + z
    """)
    fs = lint_source(src)
    assert _rules(fs) == ["AM102", "AM102", "AM102"]
    assert {f.token for f in fs} == {"time.time", "random.random", "np.random.uniform"}
    # span precision: each finding anchors to its own call line
    assert [f.line for f in fs] == [9, 10, 11]


def test_am103_bool_flag_not_static():
    src = textwrap.dedent("""
        import jax

        def run(x, training=True):
            return x

        f = jax.jit(run)
    """)
    fs = lint_source(src)
    assert _rules(fs) == ["AM103"]
    assert fs[0].token == "training"
    assert fs[0].line == 4  # the parameter's own span, not the jit site


def test_am103_static_argnames_clean():
    src = textwrap.dedent("""
        import jax

        def run(x, training=True):
            return x

        f = jax.jit(run, static_argnames=("training",))
    """)
    assert lint_source(src) == []


def test_am104_step_jit_without_donate():
    src = textwrap.dedent("""
        import jax

        def train_step(state, batch):
            return state

        f = jax.jit(train_step)
    """)
    fs = lint_source(src)
    assert _rules(fs) == ["AM104"]
    assert fs[0].line == 7  # anchored at the jit call site
    g = src.replace("jax.jit(train_step)", "jax.jit(train_step, donate_argnums=0)")
    assert lint_source(g) == []


def test_am105_bare_except_and_retry_mask():
    src = textwrap.dedent("""
        from automodel_tpu.resilience.retry import retry_call

        def load(path):
            try:
                return retry_call(open, path, policy=None)
            except Exception:
                return None

        def poll():
            try:
                return 1
            except:
                pass
    """)
    fs = lint_source(src)
    assert _rules(fs) == ["AM105", "AM105"]
    assert fs[0].token == "except-Exception" and fs[0].qualname == "load"
    assert fs[1].token == "bare-except" and fs[1].qualname == "poll"


def test_am105_reraise_is_clean():
    src = textwrap.dedent("""
        from automodel_tpu.resilience.retry import retry_call

        def load(path):
            try:
                return retry_call(open, path, policy=None)
            except Exception:
                cleanup = True
                raise
    """)
    assert lint_source(src) == []


def test_am105_plain_except_exception_without_retry_is_clean():
    """`except Exception` away from the retry surfaces is ordinary
    defensive code (FaultCrash passes through it by construction)."""
    src = textwrap.dedent("""
        def parse(s):
            try:
                return int(s)
            except Exception:
                return None
    """)
    assert lint_source(src) == []


def test_am106_telemetry_in_jit():
    """Tracer spans and registry records inside a jit-reachable body fire;
    reachability crosses into helpers like AM101's."""
    src = textwrap.dedent("""
        import jax
        from functools import partial

        def helper(x, registry):
            registry.counter("serve_steps_total", "steps").inc()
            return x

        @partial(jax.jit, donate_argnums=(0,))
        def step(pool, tracer, registry):
            tracer.instant("step.begin", step=0)
            with tracer.span("step.run"):
                pool = pool * 2
            return helper(pool, registry)
    """)
    fs = lint_source(src)
    assert _rules(fs) == ["AM106", "AM106", "AM106"]
    assert {f.token for f in fs} == {
        "registry.counter", "tracer.instant", "tracer.span",
    }
    assert {f.qualname for f in fs} == {"helper", "step"}


def test_am106_host_loop_telemetry_is_clean():
    """The sanctioned pattern — record around the jitted step from the
    host loop — does not fire, even with obs-shaped receivers in scope."""
    src = textwrap.dedent("""
        import jax
        from functools import partial

        @partial(jax.jit, donate_argnums=(0,))
        def step(pool, tok):
            return pool + tok

        def run(pool, tok, obs):
            with obs.tracer.span("step.run", step=1):
                pool = step(pool, tok)
            obs.registry.counter("serve_steps_total", "steps").inc()
            return pool
    """)
    assert lint_source(src) == []


def test_am106_non_telemetry_receivers_are_clean():
    """`.span`/`.counter` on receivers that don't look like observability
    objects (a regex match object's span, a collections.Counter) pass."""
    src = textwrap.dedent("""
        import jax
        from functools import partial

        @partial(jax.jit, donate_argnums=(0,))
        def step(pool, match, bag):
            a, b = match.span(0)
            c = bag.counter("x")
            return pool[a:b] + c
    """)
    assert lint_source(src) == []


# -- suppression + allowlist --------------------------------------------------


def test_inline_suppression_same_and_previous_line():
    src = textwrap.dedent("""
        import jax

        @jax.jit
        def fwd(x):
            a = x.item()  # lint-ok: AM101 scalar readout is the api contract
            # lint-ok: AM101 second one too
            b = x.item()
            return a + b
    """)
    assert lint_source(src) == []


def test_inline_suppression_wrong_rule_still_fires():
    src = textwrap.dedent("""
        import jax

        @jax.jit
        def fwd(x):
            return x.item()  # lint-ok: AM102 wrong rule id
    """)
    assert _rules(lint_source(src)) == ["AM101"]


def test_allowlist_requires_justification(tmp_path):
    p = tmp_path / "allow.txt"
    p.write_text("AM101 pkg/mod.py::fwd::item\n")
    with pytest.raises(AllowlistError):
        load_allowlist(str(p))
    p.write_text("AM101 pkg/mod.py::fwd::item  # device readout by design\n")
    assert load_allowlist(str(p)) == {
        "AM101 pkg/mod.py::fwd::item": "device readout by design"
    }


def test_allowlist_split_and_stale(tmp_path):
    src = textwrap.dedent("""
        import jax

        @jax.jit
        def fwd(x):
            return x.item()
    """)
    fs = lint_source(src, relpath="pkg/mod.py")
    key = fs[0].key
    allow = {key: "why", "AM102 pkg/gone.py::f::time.time": "stale"}
    kept, suppressed, stale = apply_allowlist(fs, allow)
    assert kept == [] and [f.key for f in suppressed] == [key]
    assert stale == ["AM102 pkg/gone.py::f::time.time"]


# -- HLO report parsing -------------------------------------------------------


_SYNTHETIC_HLO = """\
HloModule jit_step, is_scheduled=true, input_output_alias={ {0}: (1, {}, may-alias), {1}: (2, {0}, must-alias) }, entry_computation_layout={(f32[8]{0})->f32[8]{0}}

ENTRY %main (p0: f32[8]) -> f32[8] {
  %p0 = f32[8]{0} parameter(0)
  %ag = f32[16]{0} all-gather(f32[8]{0} %p0), replica_groups={{0,1},{2,3}}, dimensions={0}
  %agt = f32[16]{0} all-gather(f32[8]{0} %p0), replica_groups=[2,4]<=[4,2]T(1,0), dimensions={0}
  %a2a = (f32[8]{0}, f32[8]{0}) all-to-all(f32[8]{0} %p0, f32[8]{0} %p0), replica_groups=[2,4]<=[8]
  %raa = f32[8]{0} ragged-all-to-all(f32[8]{0} %p0), replica_groups={{0,1,2,3}}
  %g = f32[4]{0} gather(f32[8]{0} %p0, s32[4,1]{0} %p0), offset_dims={}
  %ds = f32[2]{0} dynamic-slice(f32[8]{0} %p0, s32[] %p0), dynamic_slice_sizes={2}
  %dus = f32[8]{0} dynamic-update-slice(f32[8]{0} %p0, f32[2]{0} %ds, s32[] %p0)
  %cv = f32[8]{0} convert(bf16[8]{0} %p0)
  %down = bf16[8]{0} convert(f32[8]{0} %p0)
  %cb = f32[8]{0} custom-call(f32[8]{0} %p0), custom_call_target="xla_python_cpu_callback"
  %tk = f32[8]{0} custom-call(f32[8]{0} %p0), custom_call_target="TopK"
}
"""


class _FakeCompiled:
    def __init__(self, txt):
        self._txt = txt

    def as_text(self):
        return self._txt

    def memory_analysis(self):
        raise AttributeError("no memory stats on this backend")


def test_analyze_synthetic_hlo():
    r = analyze_compiled(
        _FakeCompiled(_SYNTHETIC_HLO), entry="synthetic",
        mesh_axes={"dp_shard": 2, "tp": 4},
    )
    assert r.collectives == {
        "all-gather": 2, "all-reduce": 0, "reduce-scatter": 0,
        "collective-permute": 0, "all-to-all": 1, "ragged-all-to-all": 1,
    }
    # group signatures normalized + axis-annotated; the tuple-typed A2A and
    # both iota-v2 replica_groups forms (flat source and multi-dim source
    # with a transpose suffix) parse to n-groups-of-m shapes
    assert r.collective_groups == {
        "all-gather": {"2x2 (axis~dp_shard)": 1, "2x4 (axis~tp)": 1},
        "all-to-all": {"2x4 (axis~tp)": 1},
        "ragged-all-to-all": {"1x4 (axis~tp)": 1},
    }
    # "gather" does not double-count "all-gather"; "dynamic-slice" does not
    # double-count "dynamic-update-slice"
    assert r.ops == {"gather": 1, "dynamic-slice": 1, "dynamic-update-slice": 1}
    assert r.convert_upcasts == 1  # bf16->f32 only; the downcast is not one
    assert r.custom_calls == {"xla_python_cpu_callback": 1, "TopK": 1}
    assert r.host_callbacks == 1
    assert r.donation == [
        "output{0} <- param 1{} (may-alias)",
        "output{1} <- param 2{0} (must-alias)",
    ]
    assert r.memory == {}  # backend without stats: section omitted, no crash


def test_baseline_ratchet_fires_both_directions():
    base = analyze_compiled(_FakeCompiled(_SYNTHETIC_HLO), entry="synthetic")
    up = analyze_compiled(
        _FakeCompiled(_SYNTHETIC_HLO.replace(
            "%ag =", "%ag2 = f32[16]{0} all-gather(f32[8]{0} %p0), replica_groups={{0,1},{2,3}}\n  %ag =",
        )),
        entry="synthetic",
    )
    drifts = compare_report(up, base)
    assert drifts and any("all-gather" in d for d in drifts)
    down = analyze_compiled(
        _FakeCompiled(_SYNTHETIC_HLO.replace("all-to-all(", "nop(")),
        entry="synthetic",
    )
    assert compare_report(down, base)  # an "optimization" drifts too
    assert compare_report(base, base) == []


def test_structural_invariants_catch_degenerate_program():
    """check_invariants holds regardless of any baseline: a ring-CP
    program that lost its permutes (or a serve step that grew a
    collective / lost its paged gathers) violates, so --update-baselines
    refuses to pin it."""
    from automodel_tpu.analysis.entrypoints import check_invariants

    zeroed = {k: 0 for k in (
        "all-gather", "all-reduce", "reduce-scatter",
        "collective-permute", "all-to-all", "ragged-all-to-all",
    )}

    def rep(entry, coll=(), ops=()):
        return HLOReport(
            entry=entry, collectives={**zeroed, **dict(coll)},
            collective_groups={}, ops={"gather": 0, "dynamic-slice": 0,
                                       "dynamic-update-slice": 0, **dict(ops)},
            convert_upcasts=0, custom_calls={}, host_callbacks=0,
            donation=[], memory={},
        )

    assert check_invariants(rep("ring_cp_forward"))           # lost the ring
    assert check_invariants(rep(
        "paged_serve_step", coll=[("all-reduce", 1)], ops=[("gather", 9)]
    ))                                                        # grew a collective
    assert check_invariants(rep("paged_serve_step"))          # lost the gathers
    assert check_invariants(rep(
        "paged_serve_step", ops=[("gather", 9)]
    )) == []                                                  # healthy shape
    assert check_invariants(rep("unknown_entry")) == []       # no table: no-op


def test_memory_rtol():
    a = HLOReport(
        entry="m", collectives={}, collective_groups={}, ops={},
        convert_upcasts=0, custom_calls={}, host_callbacks=0, donation=[],
        memory={"peak_bytes": 1000},
    )
    b = HLOReport(
        entry="m", collectives={}, collective_groups={}, ops={},
        convert_upcasts=0, custom_calls={}, host_callbacks=0, donation=[],
        memory={"peak_bytes": 1015},
    )
    assert compare_report(b, a, mem_rtol=0.02) == []
    assert compare_report(b, a, mem_rtol=0.01)


# -- the real HLO pipeline end-to-end on a tiny program -----------------------


def test_analyze_real_compiled_program():
    """Donation + upcast + memory fields against a real compiled object
    (the five production entry points are covered in test_hlo_guards)."""

    def f(x, y):
        return (x.astype(jnp.bfloat16) @ y.astype(jnp.bfloat16)).astype(
            jnp.float32
        ) + x

    x = jnp.ones((8, 8), jnp.float32)
    c = jax.jit(f, donate_argnums=(0,)).lower(x, x).compile()
    r = analyze_compiled(c, entry="tiny")
    assert r.donation == ["output{} <- param 0{} (may-alias)"]
    assert r.convert_upcasts >= 1
    assert r.memory["argument_bytes"] == 512
    assert r.memory["peak_bytes"] > 0
    assert all(v == 0 for v in r.collectives.values())


# -- transfer-guard tripwire (the dryrun stages run the full engines) ---------


def test_transfer_guard_semantics():
    """The contract the guarded serve/train steps rely on: the sanctioned
    jnp.asarray upload stays legal, while an in-step device→host read or
    an implicit mixed-operand transfer raises."""
    with jax.transfer_guard("disallow"):
        jnp.asarray(np.ones(3))  # plan upload: allowed
    with pytest.raises(Exception, match="[Dd]isallow"):
        with jax.transfer_guard("disallow"):
            float(jnp.ones(()))  # host readout: trips
    with pytest.raises(Exception, match="[Dd]isallow"):
        with jax.transfer_guard("disallow"):
            jnp.ones(3) + np.ones(3)  # implicit operand transfer: trips


# -- the gate itself, on the package ------------------------------------------


def test_gate_lint_prong_clean_on_package():
    """`python -m automodel_tpu.analysis --lint-only` (in-process): the
    package lints clean modulo the justified allowlist; the HLO prong's
    baseline comparisons run in test_hlo_guards against the same library.
    """
    from automodel_tpu.analysis.cli import main

    assert main(["--lint-only"]) == 0


def test_gate_package_lint_has_no_unjustified_allowlist(tmp_path):
    """A finding NOT in the allowlist fails the gate (fixture package on
    disk, run through the same run_lint entry the CLI uses)."""
    import os

    from automodel_tpu.analysis.lint import lint_package

    pkg = tmp_path / "pkg"
    os.makedirs(pkg)
    (pkg / "bad.py").write_text(
        "import jax\n\n@jax.jit\ndef fwd(x):\n    return x.item()\n"
    )
    fs = lint_package(str(pkg), str(tmp_path))
    assert _rules(fs) == ["AM101"]
    kept, _, _ = apply_allowlist(fs, {})
    assert kept  # unacknowledged -> the gate exits non-zero on these
