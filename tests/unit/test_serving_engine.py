"""Serving engine: token-for-token parity vs generate(), fixed-shape step.

The acceptance contract of the continuous-batching engine:

- under greedy decoding, outputs on a RAGGED request stream (staggered
  arrivals, mixed prompt lengths, chunked prefill interleaved with decode,
  preempt-and-requeue) exactly match per-request `generate()` — for a GQA
  and an MLA decoder, on CPU;
- the decode step compiles ONCE: the jit cache-miss counter stays at 1 no
  matter how requests join/leave (the fixed-shape contract).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from automodel_tpu.inference.generate import GenerateConfig, generate
from automodel_tpu.models.llm import decoder
from automodel_tpu.models.llm.decoder import TransformerConfig
from automodel_tpu.serving import Request, ServingConfig, ServingEngine

CFG = TransformerConfig(
    vocab_size=64, hidden_size=32, intermediate_size=48, num_layers=2,
    num_heads=4, num_kv_heads=2, qk_norm=True, dtype=jnp.float32,
    remat_policy="none",
)
MLA = dataclasses.replace(
    CFG, attention_type="mla", mla_kv_lora_rank=16, mla_q_lora_rank=12,
    mla_qk_nope_head_dim=8, mla_qk_rope_head_dim=8, mla_v_head_dim=8,
)


def _ragged_prompts(lens, vocab=64, seed0=0):
    return [
        [int(t) for t in np.random.default_rng(seed0 + i).integers(1, vocab, (l,))]
        for i, l in enumerate(lens)
    ]


def _assert_parity(params, cfg, engine, prompts, arrivals, max_new):
    reqs = [
        Request(prompt=list(p), max_new_tokens=max_new, arrival=a)
        for p, a in zip(prompts, arrivals)
    ]
    res = engine.serve_batch(reqs)
    for p, out in zip(prompts, res["outputs"]):
        ref = generate(
            params, cfg, jnp.asarray([p], jnp.int32), jax.random.key(0),
            GenerateConfig(max_new_tokens=max_new),
        )
        ref_new = [int(t) for t in np.asarray(ref)[0, len(p):]]
        assert ref_new == out, f"paged engine diverged: {ref_new} vs {out}"
    return res


def test_gqa_parity_ragged_stream_compiles_once():
    """Mixed prompt lengths + staggered arrivals: chunked prefill of late
    joiners interleaves with running decodes; greedy tokens match the
    batch-synchronous path exactly and the step compiles exactly once."""
    params = decoder.init(CFG, jax.random.key(0))
    engine = ServingEngine(params, CFG, ServingConfig(
        page_size=4, num_pages=24, max_slots=3, pages_per_slot=6,
        token_budget=8, prefill_chunk=4,
    ))
    prompts = _ragged_prompts([5, 9, 3, 7, 11])
    res = _assert_parity(params, CFG, engine, prompts, [0, 0, 2, 3, 5], 6)
    # 5 requests through 3 slots: joins/leaves happened, one signature
    assert res["stats"]["compiled_signatures"] == 1
    assert engine.step_cache_size() == 1
    assert res["stats"]["new_tokens"] == 5 * 6


def test_mla_parity_ragged_stream_compiles_once():
    params = decoder.init(MLA, jax.random.key(0))
    engine = ServingEngine(params, MLA, ServingConfig(
        page_size=4, num_pages=20, max_slots=3, pages_per_slot=5,
        token_budget=6, prefill_chunk=3,
    ))
    prompts = _ragged_prompts([6, 9, 4, 8], seed0=10)
    res = _assert_parity(params, MLA, engine, prompts, [0, 1, 2, 4], 5)
    assert res["stats"]["compiled_signatures"] == 1


def test_preempt_and_requeue_parity():
    """A pool too small for every admitted request forces recompute-style
    preemption; greedy outputs stay exact (and the requeue actually ran)."""
    params = decoder.init(CFG, jax.random.key(0))
    engine = ServingEngine(params, CFG, ServingConfig(
        page_size=2, num_pages=8, max_slots=3, pages_per_slot=6,
        token_budget=6, prefill_chunk=3,
    ))
    prompts = _ragged_prompts([4, 4, 4], seed0=20)
    res = _assert_parity(params, CFG, engine, prompts, [0, 0, 0], 5)
    assert res["stats"]["preemptions"] >= 1
    assert res["stats"]["compiled_signatures"] == 1
    # preempted requests carry the audit trail
    assert sum(r.preemptions for r in res["requests"]) >= 1


def test_eos_stops_and_frees_pages():
    params = decoder.init(CFG, jax.random.key(0))
    prompt = _ragged_prompts([5], seed0=30)[0]
    # discover greedy continuation, declare its 2nd token EOS
    ref = generate(
        params, CFG, jnp.asarray([prompt], jnp.int32), jax.random.key(0),
        GenerateConfig(max_new_tokens=4),
    )
    eos = int(np.asarray(ref)[0, len(prompt) + 1])
    engine = ServingEngine(params, CFG, ServingConfig(
        page_size=4, num_pages=8, max_slots=2, pages_per_slot=4, token_budget=6,
    ))
    sched = engine.make_scheduler()
    sched.submit(Request(prompt=list(prompt), max_new_tokens=8, eos_token_id=eos))
    step = 0
    while sched.has_work:
        plan = sched.schedule(step)
        tokens, _ = engine.run_step(plan)
        sched.update(plan, tokens, step)
        step += 1
    (req,) = sched.finished
    assert req.finish_reason == "eos" and req.generated[-1] == eos
    assert len(req.generated) == 2  # stopped AT the eos, not after max_new
    assert sched.alloc.num_free == 8  # every page returned to the pool


def test_moe_decoder_parity():
    """DeepSeek shape: dense prefix + MoE stack + MLA paged cache."""
    from automodel_tpu.models.moe_lm import decoder as moe_decoder
    from automodel_tpu.models.moe_lm.decoder import MoETransformerConfig
    from automodel_tpu.moe.config import MoEConfig

    cfg = MoETransformerConfig(
        vocab_size=64, hidden_size=32, intermediate_size=48, num_layers=3,
        num_heads=4, num_kv_heads=4, first_k_dense=1, dtype=jnp.float32,
        remat_policy="none",
        attention_type="mla", mla_kv_lora_rank=16, mla_q_lora_rank=12,
        mla_qk_nope_head_dim=8, mla_qk_rope_head_dim=8, mla_v_head_dim=8,
        moe=MoEConfig(
            n_routed_experts=4, n_shared_experts=1, experts_per_token=2,
            moe_intermediate_size=16, shared_expert_intermediate_size=16,
            aux_loss_coeff=0.0, dispatcher="dropless",
        ),
    )
    params = moe_decoder.init(cfg, jax.random.key(0))
    engine = ServingEngine(params, cfg, ServingConfig(
        page_size=4, num_pages=16, max_slots=2, pages_per_slot=4,
        token_budget=6, prefill_chunk=3,
    ))
    prompts = _ragged_prompts([5, 7], seed0=40)
    res = _assert_parity(params, cfg, engine, prompts, [0, 1], 4)
    assert res["stats"]["compiled_signatures"] == 1


@pytest.mark.slow
def test_windows_and_sinks_parity():
    """gemma2/gpt-oss shape (alternating windows + sinks) takes the XLA
    paged path; greedy parity must hold there too."""
    cfg = dataclasses.replace(
        CFG, qk_norm=False, sliding_window=4,
        layer_types=("sliding", "global"), attention_sinks=True,
    )
    params = decoder.init(cfg, jax.random.key(0))
    params["layers"]["sinks"] = 0.5 + 0.1 * jax.random.normal(
        jax.random.key(11), params["layers"]["sinks"].shape
    )
    engine = ServingEngine(params, cfg, ServingConfig(
        page_size=4, num_pages=16, max_slots=2, pages_per_slot=4,
        token_budget=6, prefill_chunk=3,
    ))
    prompts = _ragged_prompts([5, 7], seed0=50)
    _assert_parity(params, cfg, engine, prompts, [0, 0], 4)


@pytest.mark.slow
def test_sampling_deterministic_across_batching():
    """Sampling keys derive from (request seed, position): the same request
    yields the same tokens no matter the engine geometry, co-resident
    traffic, or preemptions."""
    params = decoder.init(CFG, jax.random.key(0))
    prompt = _ragged_prompts([5], seed0=60)[0]

    def run(serve_cfg, extra=()):
        engine = ServingEngine(params, CFG, serve_cfg)
        reqs = [Request(prompt=list(prompt), max_new_tokens=5,
                        temperature=0.8, seed=7)]
        reqs += [Request(prompt=list(p), max_new_tokens=4, seed=1 + i)
                 for i, p in enumerate(extra)]
        return engine.serve_batch(reqs)["outputs"][0]

    a = run(ServingConfig(page_size=4, num_pages=16, max_slots=2,
                          pages_per_slot=4, token_budget=6))
    b = run(
        ServingConfig(page_size=2, num_pages=20, max_slots=3,
                      pages_per_slot=8, token_budget=4, prefill_chunk=2),
        extra=_ragged_prompts([6, 3], seed0=70),
    )
    assert a == b
    assert all(0 <= t < 64 for t in a)


@pytest.mark.slow
def test_defrag_preserves_decode():
    """Compacting the pool mid-run (tables rewritten + device gather) must
    not change subsequent decode output."""
    params = decoder.init(CFG, jax.random.key(0))
    engine = ServingEngine(params, CFG, ServingConfig(
        page_size=2, num_pages=16, max_slots=3, pages_per_slot=8,
        token_budget=6,
    ))
    prompts = _ragged_prompts([4, 5, 3], seed0=80)
    sched = engine.make_scheduler()
    for i, p in enumerate(prompts):
        sched.submit(Request(prompt=list(p), max_new_tokens=6))
    step = 0
    while sched.has_work:
        plan = sched.schedule(step)
        if plan is not None:
            tokens, _ = engine.run_step(plan)
            sched.update(plan, tokens, step)
            if step == 4:
                # finishings have punched holes by now; force compaction
                engine.defrag(sched)
        step += 1
    for p, req in zip(prompts, sorted(sched.finished, key=lambda r: r.rid)):
        ref = generate(
            params, CFG, jnp.asarray([p], jnp.int32), jax.random.key(0),
            GenerateConfig(max_new_tokens=6),
        )
        assert [int(t) for t in np.asarray(ref)[0, len(p):]] == req.generated


def test_het_engine_rejected():
    from automodel_tpu.serving.engine import ServingEngine as SE

    class FakeHet:  # avoid building real het params just for the raise
        pass

    from automodel_tpu.models.moe_lm.het_moe import HetMoEConfig

    cfg = HetMoEConfig(
        num_layers=1, layer_types=("global",), mlp_kinds=("dense",),
    )
    with pytest.raises(NotImplementedError):
        SE({}, cfg, ServingConfig())
