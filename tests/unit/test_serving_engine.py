"""Serving engine: token-for-token parity vs generate(), fixed-shape step.

The acceptance contract of the continuous-batching engine:

- under greedy decoding, outputs on a RAGGED request stream (staggered
  arrivals, mixed prompt lengths, chunked prefill interleaved with decode,
  preempt-and-requeue) exactly match per-request `generate()` — for a GQA
  and an MLA decoder, on CPU;
- the decode step compiles ONCE: the jit cache-miss counter stays at 1 no
  matter how requests join/leave (the fixed-shape contract).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from automodel_tpu.inference.generate import GenerateConfig, generate
from automodel_tpu.models.llm import decoder
from automodel_tpu.models.llm.decoder import TransformerConfig
from automodel_tpu.serving import Request, ServingConfig, ServingEngine

CFG = TransformerConfig(
    vocab_size=64, hidden_size=32, intermediate_size=48, num_layers=2,
    num_heads=4, num_kv_heads=2, qk_norm=True, dtype=jnp.float32,
    remat_policy="none",
)
MLA = dataclasses.replace(
    CFG, attention_type="mla", mla_kv_lora_rank=16, mla_q_lora_rank=12,
    mla_qk_nope_head_dim=8, mla_qk_rope_head_dim=8, mla_v_head_dim=8,
)


def _ragged_prompts(lens, vocab=64, seed0=0):
    return [
        [int(t) for t in np.random.default_rng(seed0 + i).integers(1, vocab, (l,))]
        for i, l in enumerate(lens)
    ]


def _assert_parity(params, cfg, engine, prompts, arrivals, max_new):
    reqs = [
        Request(prompt=list(p), max_new_tokens=max_new, arrival=a)
        for p, a in zip(prompts, arrivals)
    ]
    res = engine.serve_batch(reqs)
    for p, out in zip(prompts, res["outputs"]):
        ref = generate(
            params, cfg, jnp.asarray([p], jnp.int32), jax.random.key(0),
            GenerateConfig(max_new_tokens=max_new),
        )
        ref_new = [int(t) for t in np.asarray(ref)[0, len(p):]]
        assert ref_new == out, f"paged engine diverged: {ref_new} vs {out}"
    return res


def test_gqa_parity_ragged_stream_compiles_once():
    """Mixed prompt lengths + staggered arrivals: chunked prefill of late
    joiners interleaves with running decodes; greedy tokens match the
    batch-synchronous path exactly and the step compiles exactly once."""
    params = decoder.init(CFG, jax.random.key(0))
    engine = ServingEngine(params, CFG, ServingConfig(
        page_size=4, num_pages=24, max_slots=3, pages_per_slot=6,
        token_budget=8, prefill_chunk=4,
    ))
    prompts = _ragged_prompts([5, 9, 3, 7, 11])
    res = _assert_parity(params, CFG, engine, prompts, [0, 0, 2, 3, 5], 6)
    # 5 requests through 3 slots: joins/leaves happened, one signature
    assert res["stats"]["compiled_signatures"] == 1
    assert engine.step_cache_size() == 1
    assert res["stats"]["new_tokens"] == 5 * 6


def test_mla_parity_ragged_stream_compiles_once():
    params = decoder.init(MLA, jax.random.key(0))
    engine = ServingEngine(params, MLA, ServingConfig(
        page_size=4, num_pages=20, max_slots=3, pages_per_slot=5,
        token_budget=6, prefill_chunk=3,
    ))
    prompts = _ragged_prompts([6, 9, 4, 8], seed0=10)
    res = _assert_parity(params, MLA, engine, prompts, [0, 1, 2, 4], 5)
    assert res["stats"]["compiled_signatures"] == 1


def test_preempt_and_requeue_parity():
    """A pool too small for every admitted request forces recompute-style
    preemption; greedy outputs stay exact (and the requeue actually ran)."""
    params = decoder.init(CFG, jax.random.key(0))
    engine = ServingEngine(params, CFG, ServingConfig(
        page_size=2, num_pages=8, max_slots=3, pages_per_slot=6,
        token_budget=6, prefill_chunk=3,
    ))
    prompts = _ragged_prompts([4, 4, 4], seed0=20)
    res = _assert_parity(params, CFG, engine, prompts, [0, 0, 0], 5)
    assert res["stats"]["preemptions"] >= 1
    assert res["stats"]["compiled_signatures"] == 1
    # preempted requests carry the audit trail
    assert sum(r.preemptions for r in res["requests"]) >= 1


def test_eos_stops_and_frees_pages():
    params = decoder.init(CFG, jax.random.key(0))
    prompt = _ragged_prompts([5], seed0=30)[0]
    # discover greedy continuation, declare its 2nd token EOS
    ref = generate(
        params, CFG, jnp.asarray([prompt], jnp.int32), jax.random.key(0),
        GenerateConfig(max_new_tokens=4),
    )
    eos = int(np.asarray(ref)[0, len(prompt) + 1])
    engine = ServingEngine(params, CFG, ServingConfig(
        page_size=4, num_pages=8, max_slots=2, pages_per_slot=4, token_budget=6,
    ))
    sched = engine.make_scheduler()
    sched.submit(Request(prompt=list(prompt), max_new_tokens=8, eos_token_id=eos))
    step = 0
    while sched.has_work:
        plan = sched.schedule(step)
        tokens, _ = engine.run_step(plan)
        sched.update(plan, tokens, step)
        step += 1
    (req,) = sched.finished
    assert req.finish_reason == "eos" and req.generated[-1] == eos
    assert len(req.generated) == 2  # stopped AT the eos, not after max_new
    assert sched.alloc.num_free == 8  # every page returned to the pool


def test_moe_decoder_parity():
    """DeepSeek shape: dense prefix + MoE stack + MLA paged cache."""
    from automodel_tpu.models.moe_lm import decoder as moe_decoder
    from automodel_tpu.models.moe_lm.decoder import MoETransformerConfig
    from automodel_tpu.moe.config import MoEConfig

    cfg = MoETransformerConfig(
        vocab_size=64, hidden_size=32, intermediate_size=48, num_layers=3,
        num_heads=4, num_kv_heads=4, first_k_dense=1, dtype=jnp.float32,
        remat_policy="none",
        attention_type="mla", mla_kv_lora_rank=16, mla_q_lora_rank=12,
        mla_qk_nope_head_dim=8, mla_qk_rope_head_dim=8, mla_v_head_dim=8,
        moe=MoEConfig(
            n_routed_experts=4, n_shared_experts=1, experts_per_token=2,
            moe_intermediate_size=16, shared_expert_intermediate_size=16,
            aux_loss_coeff=0.0, dispatcher="dropless",
        ),
    )
    params = moe_decoder.init(cfg, jax.random.key(0))
    engine = ServingEngine(params, cfg, ServingConfig(
        page_size=4, num_pages=16, max_slots=2, pages_per_slot=4,
        token_budget=6, prefill_chunk=3,
    ))
    prompts = _ragged_prompts([5, 7], seed0=40)
    res = _assert_parity(params, cfg, engine, prompts, [0, 1], 4)
    assert res["stats"]["compiled_signatures"] == 1


@pytest.mark.slow
def test_windows_and_sinks_parity():
    """gemma2/gpt-oss shape (alternating windows + sinks) takes the XLA
    paged path; greedy parity must hold there too."""
    cfg = dataclasses.replace(
        CFG, qk_norm=False, sliding_window=4,
        layer_types=("sliding", "global"), attention_sinks=True,
    )
    params = decoder.init(cfg, jax.random.key(0))
    params["layers"]["sinks"] = 0.5 + 0.1 * jax.random.normal(
        jax.random.key(11), params["layers"]["sinks"].shape
    )
    engine = ServingEngine(params, cfg, ServingConfig(
        page_size=4, num_pages=16, max_slots=2, pages_per_slot=4,
        token_budget=6, prefill_chunk=3,
    ))
    prompts = _ragged_prompts([5, 7], seed0=50)
    _assert_parity(params, cfg, engine, prompts, [0, 0], 4)


@pytest.mark.slow
def test_sampling_deterministic_across_batching():
    """Sampling keys derive from (request seed, position): the same request
    yields the same tokens no matter the engine geometry, co-resident
    traffic, or preemptions."""
    params = decoder.init(CFG, jax.random.key(0))
    prompt = _ragged_prompts([5], seed0=60)[0]

    def run(serve_cfg, extra=()):
        engine = ServingEngine(params, CFG, serve_cfg)
        reqs = [Request(prompt=list(prompt), max_new_tokens=5,
                        temperature=0.8, seed=7)]
        reqs += [Request(prompt=list(p), max_new_tokens=4, seed=1 + i)
                 for i, p in enumerate(extra)]
        return engine.serve_batch(reqs)["outputs"][0]

    a = run(ServingConfig(page_size=4, num_pages=16, max_slots=2,
                          pages_per_slot=4, token_budget=6))
    b = run(
        ServingConfig(page_size=2, num_pages=20, max_slots=3,
                      pages_per_slot=8, token_budget=4, prefill_chunk=2),
        extra=_ragged_prompts([6, 3], seed0=70),
    )
    assert a == b
    assert all(0 <= t < 64 for t in a)


@pytest.mark.slow
def test_defrag_preserves_decode():
    """Compacting the pool mid-run (tables rewritten + device gather) must
    not change subsequent decode output."""
    params = decoder.init(CFG, jax.random.key(0))
    engine = ServingEngine(params, CFG, ServingConfig(
        page_size=2, num_pages=16, max_slots=3, pages_per_slot=8,
        token_budget=6,
    ))
    prompts = _ragged_prompts([4, 5, 3], seed0=80)
    sched = engine.make_scheduler()
    for i, p in enumerate(prompts):
        sched.submit(Request(prompt=list(p), max_new_tokens=6))
    step = 0
    while sched.has_work:
        plan = sched.schedule(step)
        if plan is not None:
            tokens, _ = engine.run_step(plan)
            sched.update(plan, tokens, step)
            if step == 4:
                # finishings have punched holes by now; force compaction
                engine.defrag(sched)
        step += 1
    for p, req in zip(prompts, sorted(sched.finished, key=lambda r: r.rid)):
        ref = generate(
            params, CFG, jnp.asarray([p], jnp.int32), jax.random.key(0),
            GenerateConfig(max_new_tokens=6),
        )
        assert [int(t) for t in np.asarray(ref)[0, len(p):]] == req.generated


_OVERLOAD = dict(
    page_size=2, num_pages=8, max_slots=2, pages_per_slot=8,
    token_budget=8, prefill_chunk=4,
)


def _overload_stream(deadline):
    """A pool-hogging request (grows toward the WHOLE pool) + a smaller
    late joiner: together they oversubscribe the pool, so the stream only
    progresses by preempt-and-requeue churn until one of them leaves."""
    hog_prompt, blocked_prompt = _ragged_prompts([8, 6], seed0=90)
    return (
        Request(prompt=list(hog_prompt), max_new_tokens=8, deadline=deadline),
        Request(prompt=list(blocked_prompt), max_new_tokens=3, arrival=1),
        blocked_prompt,
    )


def test_deadline_evicts_pool_hog_from_stalled_stream():
    """Graceful degradation under overload: the hog's deadline evicts it
    mid-generation (pages freed, reported `timed_out`) instead of occupying
    pool pages for the rest of its decode; the co-resident request then
    runs without further churn and its output keeps exact greedy parity."""
    params = decoder.init(CFG, jax.random.key(0))
    engine = ServingEngine(params, CFG, ServingConfig(**_OVERLOAD))
    hog, blocked, blocked_prompt = _overload_stream(deadline=6)
    res = engine.serve_batch([hog, blocked])
    stats = res["stats"]
    assert stats["timed_out"] == 1 and stats["requests"] == 2
    a, b = res["requests"]
    assert a.finish_reason == "timed_out" and a.finished_at == 6
    assert 0 < len(a.generated) < 8  # partial generation survives eviction
    # the surviving request matches the batch-synchronous path exactly
    ref = generate(
        params, CFG, jnp.asarray([blocked_prompt], jnp.int32), jax.random.key(0),
        GenerateConfig(max_new_tokens=3),
    )
    assert b.finish_reason == "length"
    assert [int(t) for t in np.asarray(ref)[0, len(blocked_prompt):]] == b.generated
    assert res["stats"]["compiled_signatures"] == 1
    # eviction relieved the overload: strictly fewer engine steps than the
    # churning no-deadline run of the same stream (see companion test)
    assert b.finished_at <= 11


def test_no_deadline_same_stream_churns_but_completes():
    """The same overload stream WITHOUT a deadline completes only through
    preempt-and-requeue churn (the victim re-prefills from scratch), and
    the smaller request finishes AFTER the hog despite needing 3 tokens —
    the latency cliff the per-request deadline bounds."""
    params = decoder.init(CFG, jax.random.key(0))
    engine = ServingEngine(params, CFG, ServingConfig(**_OVERLOAD))
    hog, blocked, _ = _overload_stream(deadline=None)
    res = engine.serve_batch([hog, blocked])
    assert res["stats"]["timed_out"] == 0
    a, b = res["requests"]
    assert a.finish_reason == "length" and len(a.generated) == 8
    assert b.preemptions >= 1            # pool churn, recompute-style
    assert b.finished_at > a.finished_at  # 3-token request served LAST


def test_deadline_fast_forward_never_skips_a_future_arrival():
    """The serve loop's idle fast-forward to the next deadline must not
    jump PAST a future arrival — the request would be expired without ever
    getting its window to run."""
    params = decoder.init(CFG, jax.random.key(0))
    engine = ServingEngine(params, CFG, ServingConfig(
        page_size=4, num_pages=16, max_slots=2, pages_per_slot=4,
        token_budget=8,
    ))
    (prompt,) = _ragged_prompts([5], seed0=95)
    res = engine.serve_batch([
        Request(prompt=list(prompt), max_new_tokens=3, arrival=5, deadline=100),
    ])
    (req,) = res["requests"]
    assert req.finish_reason == "length" and len(req.generated) == 3
    assert res["stats"]["timed_out"] == 0


def test_deadline_expires_waiting_request_without_pages():
    """A request whose deadline passes while it is still QUEUED leaves with
    zero generated tokens and never touches the pool."""
    from automodel_tpu.serving.scheduler import Scheduler

    sched = Scheduler(
        num_pages=8, page_size=2, max_slots=1, pages_per_slot=8,
        token_budget=8,
    )
    sched.submit(Request(prompt=[1, 2, 3, 4, 5, 6], max_new_tokens=6))
    sched.submit(Request(prompt=[7, 8], max_new_tokens=2, deadline=2))
    free0 = sched.alloc.num_free
    plan = sched.schedule(0)  # only the first request admits (max_slots=1)
    assert plan is not None and len(sched.running) == 1
    sched.schedule(3)  # past the waiter's deadline
    timed_out = [r for r in sched.finished if r.finish_reason == "timed_out"]
    assert len(timed_out) == 1 and timed_out[0].generated == []
    assert sched.n_timed_out == 1
    assert sched.alloc.num_free < free0  # only the running request holds pages


def test_het_engine_rejected():
    from automodel_tpu.serving.engine import ServingEngine as SE

    class FakeHet:  # avoid building real het params just for the raise
        pass

    from automodel_tpu.models.moe_lm.het_moe import HetMoEConfig

    cfg = HetMoEConfig(
        num_layers=1, layer_types=("global",), mlp_kinds=("dense",),
    )
    with pytest.raises(NotImplementedError):
        SE({}, cfg, ServingConfig())
