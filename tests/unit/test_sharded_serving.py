"""Pod-scale serving: mesh-sharded pool + TP/EP step + DP replica router.

The acceptance contract of the sharded engine (docs/SERVING.md §"Sharded
serving"):

- token-for-token greedy parity tp1 vs tp2 vs dp2×tp2 on a CPU mesh over
  ragged streams — staggered arrivals, forced preemption, prefix-cache
  hits, and speculation enabled — against the single-chip engine (whose
  own parity vs generate() is pinned in test_serving_engine.py);
- compile-once per replica via the jit cache-miss counter (the sharded
  step's in/out shardings are pinned so the donated pool's normalized
  output sharding can never re-cut the cache);
- the MLA pool shards its LATENT rank, the GQA pool its KV heads; MoE
  decoders run PR 1's dropless EP dispatch inside the step;
- the router's per-replica admission: least-loaded-by-free-pages with
  sticky prefix-cache affinity.

The compiled collective structure of the tp2 step is pinned separately by
the `sharded_serve_step` analysis baseline (test_hlo_guards).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from automodel_tpu.distributed import MeshConfig
from automodel_tpu.models.llm import decoder
from automodel_tpu.models.llm.decoder import TransformerConfig
from automodel_tpu.serving import (
    PrefixCacheConfig,
    ReplicaRouter,
    Request,
    ServeMeshConfig,
    ServingConfig,
    ServingEngine,
    SpeculativeConfig,
)

CFG = TransformerConfig(
    vocab_size=64, hidden_size=32, intermediate_size=48, num_layers=2,
    num_heads=4, num_kv_heads=2, qk_norm=True, dtype=jnp.float32,
    remat_policy="none",
)
MLA = dataclasses.replace(
    CFG, qk_norm=False, attention_type="mla", mla_kv_lora_rank=16,
    mla_q_lora_rank=12, mla_qk_nope_head_dim=8, mla_qk_rope_head_dim=8,
    mla_v_head_dim=8,
)


def _prompts(lens, seed0=0):
    return [
        [int(t) for t in np.random.default_rng(seed0 + i).integers(1, 64, (l,))]
        for i, l in enumerate(lens)
    ]


def _reqs(prompts, arrivals, max_new=6):
    return [
        Request(prompt=list(p), max_new_tokens=max_new, arrival=a)
        for p, a in zip(prompts, arrivals)
    ]


def _tp_ctx(tp):
    return MeshConfig(tp=tp, dp_shard=1).build(jax.devices()[:tp])


def _serve(params, cfg, mesh_ctx, sc, requests):
    eng = ServingEngine(params, cfg, sc, mesh_ctx=mesh_ctx)
    res = eng.serve_batch(requests)
    assert res["stats"]["compiled_signatures"] == 1, res["stats"]
    return res


def test_tp2_parity_ragged_stream_with_preemption():
    """GQA tp2 (KV-head-sharded pool): greedy tokens equal the single-chip
    engine's on a ragged stream whose tight pool forces recompute-style
    preemption — and the trivial 1-device mesh rides the same code path."""
    params = decoder.init(CFG, jax.random.key(0))
    sc = ServingConfig(
        page_size=2, num_pages=8, max_slots=3, pages_per_slot=6,
        token_budget=6, prefill_chunk=3,
    )
    requests = lambda: _reqs(_prompts([4, 4, 4], 20), [0, 0, 0], 5)  # noqa: E731
    base = _serve(params, CFG, None, sc, requests())
    tp1 = _serve(params, CFG, _tp_ctx(1), sc, requests())
    tp2 = _serve(params, CFG, _tp_ctx(2), sc, requests())
    assert tp1["outputs"] == base["outputs"]
    assert tp2["outputs"] == base["outputs"]
    assert tp2["stats"]["preemptions"] >= 1  # the churn actually happened


def test_tp2_parity_prefix_cache_and_speculation():
    """Prefix sharing (radix hits + COW) and draft-then-verify compose
    with the sharded step: tokens equal the plain single-chip engine's,
    hits and drafts actually fire, one compiled signature."""
    params = decoder.init(CFG, jax.random.key(0))
    rng = np.random.default_rng(1)
    system = [int(t) for t in rng.integers(1, 64, (8,))]
    prompts = [
        system + [int(t) for t in rng.integers(1, 64, (3,))],
        system + [int(t) for t in rng.integers(1, 64, (2,))],
    ]
    geo = dict(page_size=4, num_pages=32, max_slots=2, pages_per_slot=8,
               token_budget=8, prefill_chunk=4)
    base = _serve(
        params, CFG, None, ServingConfig(**geo), _reqs(prompts, (0, 2)),
    )
    tp2 = _serve(
        params, CFG, _tp_ctx(2),
        ServingConfig(
            **geo,
            prefix_cache=PrefixCacheConfig(enabled=True),
            speculative=SpeculativeConfig(enabled=True, draft_len=4),
        ),
        _reqs(prompts, (0, 2)),
    )
    assert tp2["outputs"] == base["outputs"]
    assert tp2["stats"]["prefix_hits"] >= 1, tp2["stats"]
    assert tp2["stats"]["drafted_tokens"] >= 1, tp2["stats"]


def test_mla_tp2_latent_sharded_parity():
    """Absorbed-MLA pool under tp2 shards the kv-latent rank (heads share
    one latent — there is no head dim to cut); greedy parity must hold
    through the latent-parallel attention algebra."""
    params = decoder.init(MLA, jax.random.key(0))
    sc = ServingConfig(
        page_size=4, num_pages=20, max_slots=3, pages_per_slot=5,
        token_budget=6, prefill_chunk=3,
    )
    requests = lambda: _reqs(_prompts([6, 9, 4], 10), [0, 1, 2], 5)  # noqa: E731
    base = _serve(params, MLA, None, sc, requests())
    tp2 = _serve(params, MLA, _tp_ctx(2), sc, requests())
    assert tp2["outputs"] == base["outputs"]
    # the latent pool is genuinely partitioned: each rank holds r/tp
    eng = ServingEngine(params, MLA, sc, mesh_ctx=_tp_ctx(2))
    c_shard = eng.pool[0][0].sharding
    assert c_shard.spec[3] == "tp", c_shard


def test_dp2_tp2_router_parity_balance_and_compile_once():
    """dp2×tp2: two tp2 replicas behind the router emit the exact
    single-chip token stream; admission is least-loaded (both replicas
    get work) and each replica keeps ONE compiled signature."""
    params = decoder.init(CFG, jax.random.key(0))
    sc = ServingConfig(
        page_size=4, num_pages=24, max_slots=3, pages_per_slot=6,
        token_budget=8, prefill_chunk=4,
    )
    prompts = _prompts([5, 9, 3, 7, 11, 4])
    arrivals = [0, 0, 1, 2, 3, 4]
    base = ServingEngine(params, CFG, sc).serve_batch(
        _reqs(prompts, arrivals)
    )
    router = ReplicaRouter(
        params, CFG, sc, ServeMeshConfig(replicas=2, tp=2),
    )
    res = router.serve_batch(_reqs(prompts, arrivals))
    st = res["stats"]
    assert res["outputs"] == base["outputs"]
    assert st["compiled_signatures"] == 1, st
    assert all(
        pr["compiled_signatures"] == 1 for pr in st["per_replica"]
    ), st
    assert min(st["requests_per_replica"]) >= 1, st
    assert sum(st["tokens_per_replica"]) == st["new_tokens"]
    assert 0 < st["balance"] <= 1


def test_router_sticky_prefix_affinity():
    """A later request sharing a cached prefix routes to the replica that
    already holds the pages (and admits as a radix hit there) even when
    the other replica has more free pages."""
    params = decoder.init(CFG, jax.random.key(0))
    rng = np.random.default_rng(7)
    system = [int(t) for t in rng.integers(1, 64, (8,))]
    reqs = [
        Request(
            prompt=system + [int(t) for t in rng.integers(1, 64, (3,))],
            max_new_tokens=4, arrival=0,
        ),
        Request(
            prompt=system + [int(t) for t in rng.integers(1, 64, (2,))],
            max_new_tokens=4, arrival=6,
        ),
    ]
    router = ReplicaRouter(
        params, CFG,
        ServingConfig(
            page_size=4, num_pages=24, max_slots=3, pages_per_slot=6,
            token_budget=8, prefill_chunk=4,
            prefix_cache=PrefixCacheConfig(enabled=True),
        ),
        ServeMeshConfig(replicas=2, tp=1),
    )
    st = router.serve_batch(reqs)["stats"]
    assert st["sticky_routed"] >= 1, st
    assert st["prefix_hits"] >= 1, st
    # both landed on one replica — affinity beat least-loaded
    assert sorted(st["requests_per_replica"]) == [0, 2], st


def test_moe_ep2_expert_dispatch_inside_step():
    """DeepSeek shape (dense prefix + MoE stack + MLA cache) under ep2:
    the dropless EP shard_map (expert A2A inside the step) commits the
    exact single-shard token stream."""
    from automodel_tpu.models.moe_lm import decoder as moe_decoder
    from automodel_tpu.models.moe_lm.decoder import MoETransformerConfig
    from automodel_tpu.moe.config import MoEConfig

    cfg = MoETransformerConfig(
        vocab_size=64, hidden_size=32, intermediate_size=48, num_layers=3,
        num_heads=4, num_kv_heads=4, first_k_dense=1, dtype=jnp.float32,
        remat_policy="none",
        attention_type="mla", mla_kv_lora_rank=16, mla_q_lora_rank=12,
        mla_qk_nope_head_dim=8, mla_qk_rope_head_dim=8, mla_v_head_dim=8,
        moe=MoEConfig(
            n_routed_experts=4, n_shared_experts=1, experts_per_token=2,
            moe_intermediate_size=16, shared_expert_intermediate_size=16,
            aux_loss_coeff=0.0, dispatcher="dropless",
        ),
    )
    params = moe_decoder.init(cfg, jax.random.key(0))
    sc = ServingConfig(
        page_size=4, num_pages=16, max_slots=2, pages_per_slot=4,
        token_budget=6, prefill_chunk=3,
    )
    requests = lambda: _reqs(_prompts([5, 7], 40), [0, 1], 4)  # noqa: E731
    base = _serve(params, cfg, None, sc, requests())
    ctx = MeshConfig(ep=2, dp_shard=1).build(jax.devices()[:2])
    ep2 = _serve(params, cfg, ctx, sc, requests())
    assert ep2["outputs"] == base["outputs"]


def test_tp2_defrag_preserves_decode_and_sharding():
    """Pool compaction under tp2: the defrag gather rides the sharded
    (donated) pool — page IDs stay global so the host plan is unchanged,
    the head shards move together, and subsequent decode is unaffected."""
    from automodel_tpu.inference.generate import GenerateConfig, generate

    params = decoder.init(CFG, jax.random.key(0))
    eng = ServingEngine(params, CFG, ServingConfig(
        page_size=2, num_pages=16, max_slots=3, pages_per_slot=8,
        token_budget=6,
    ), mesh_ctx=_tp_ctx(2))
    prompts = _prompts([4, 5, 3], seed0=80)
    sched = eng.make_scheduler()
    for p in prompts:
        sched.submit(Request(prompt=list(p), max_new_tokens=6))
    step = 0
    while sched.has_work:
        plan = sched.schedule(step)
        if plan is not None:
            eng.run_and_absorb(sched, plan, step)
            if step == 4:
                eng.defrag(sched)
                assert eng.pool[0][0].sharding.spec[3] == "tp"
        step += 1
    for p, req in zip(prompts, sorted(sched.finished, key=lambda r: r.rid)):
        ref = generate(
            params, CFG, jnp.asarray([p], jnp.int32), jax.random.key(0),
            GenerateConfig(max_new_tokens=6),
        )
        assert [int(t) for t in np.asarray(ref)[0, len(p):]] == req.generated


def test_mesh_validation_errors():
    """The engine rejects meshes it cannot shard: non-tp/ep axes, GQA head
    indivisibility, ep without MoE, token budgets the EP shard_map cannot
    split — loud errors, not silent replication."""
    params = decoder.init(CFG, jax.random.key(0))
    sc = ServingConfig(page_size=4, num_pages=8, max_slots=2,
                       pages_per_slot=4, token_budget=4)
    with pytest.raises(ValueError, match="dp_shard=1"):
        ServingEngine(
            params, CFG, sc,
            mesh_ctx=MeshConfig(dp_shard=2).build(jax.devices()[:2]),
        )
    bad_heads = dataclasses.replace(CFG, num_kv_heads=3, num_heads=3)
    with pytest.raises(ValueError, match="divisible by tp"):
        ServingEngine(params, bad_heads, sc, mesh_ctx=_tp_ctx(2))
    with pytest.raises(ValueError, match="MoE"):
        ServingEngine(
            params, CFG, sc,
            mesh_ctx=MeshConfig(ep=2, dp_shard=1).build(jax.devices()[:2]),
        )
    with pytest.raises(ValueError, match="devices"):
        ServeMeshConfig(replicas=8, tp=2).build_contexts()


@pytest.mark.slow
def test_tp2_eagle_hidden_feedback_host_addressable():
    """EAGLE speculation under tp2: the frontier hidden feedback is
    gathered per-slot from the sharded step (replicated output), so the
    host-side drafter state machinery works unchanged — and greedy
    verification keeps the committed stream token-exact regardless of
    draft quality."""
    from automodel_tpu.models.llm.decoder import head_kernel
    from automodel_tpu.serving import EagleDraftSource
    from automodel_tpu.speculative.eagle1 import Eagle1Config, init_drafter

    params = decoder.init(CFG, jax.random.key(0))
    ecfg = Eagle1Config(
        vocab_size=64, hidden_size=32, intermediate_size=48,
        num_heads=4, num_kv_heads=2, num_layers=1,
    )
    sc_kw = dict(page_size=4, num_pages=32, max_slots=2, pages_per_slot=8,
                 token_budget=8, prefill_chunk=4)
    requests = lambda: _reqs(_prompts([5, 9], 60), [0, 1], 6)  # noqa: E731
    base = _serve(params, CFG, None, ServingConfig(**sc_kw), requests())
    eng = ServingEngine(
        params, CFG,
        ServingConfig(
            **sc_kw,
            speculative=SpeculativeConfig(
                enabled=True, draft_source="eagle", draft_len=3,
            ),
        ),
        draft_source=EagleDraftSource(
            init_drafter(ecfg, jax.random.key(1)), ecfg,
            head_kernel(params, CFG), draft_len=3, window=8,
        ),
        mesh_ctx=_tp_ctx(2),
    )
    res = eng.serve_batch(requests())
    assert res["outputs"] == base["outputs"]
    assert res["stats"]["compiled_signatures"] == 1
