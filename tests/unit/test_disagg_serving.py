"""Disaggregated prefill/decode serving + engine-lifetime prefix cache.

The acceptance contract of the disaggregation tier (docs/SERVING.md
§"Disaggregated serving"):

- token-for-token greedy parity DisaggRouter vs the monolithic engine on
  ragged streams — staggered arrivals, prefix-cache hits, decode-side
  speculation, and forced preemption of already-handed-off requests —
  with ONE compiled step signature per replica class (prefill's wider
  token budget compiles its own program; neither class recompiles);
- KV handoff edge cases: a handoff racing its request's deadline expires
  in flight with every prefill-side pin released; a half-transferred
  (admitted-then-preempted) request requeues and still finishes right;
  transferred pages spliced against the decode replica's radix tree keep
  allocator refcounts consistent to the last page;
- the engine-lifetime prefix cache: allocator + radix tree now survive
  across `serve_batch` calls, so a second batch re-serves the first
  call's system prompt with most of its prefill skipped — and
  `reset_prefix_cache()` returns the engine to cold.

The fused transfer program's compiled structure (gather/scatter only,
zero collectives, destination donation) is pinned separately by the
`kv_transfer` analysis baseline (test_hlo_guards).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from automodel_tpu.models.llm import decoder
from automodel_tpu.models.llm.decoder import TransformerConfig
from automodel_tpu.serving import (
    DisaggConfig,
    DisaggRouter,
    KVTransfer,
    PrefixCacheConfig,
    Request,
    ServingConfig,
    ServingEngine,
    SpeculativeConfig,
)

CFG = TransformerConfig(
    vocab_size=64, hidden_size=32, intermediate_size=48, num_layers=2,
    num_heads=4, num_kv_heads=2, qk_norm=True, dtype=jnp.float32,
    remat_policy="none",
)


@pytest.fixture(scope="module")
def params():
    return decoder.init(CFG, jax.random.key(0))


def _prompts(lens, seed0=0):
    return [
        [int(t) for t in np.random.default_rng(seed0 + i).integers(1, 64, (l,))]
        for i, l in enumerate(lens)
    ]


def _reqs(prompts, arrivals, max_new=6):
    return [
        Request(prompt=list(p), max_new_tokens=max_new, arrival=a)
        for p, a in zip(prompts, arrivals)
    ]


def _mono(params, sc, requests):
    res = ServingEngine(params, CFG, sc).serve_batch(requests)
    assert res["stats"]["compiled_signatures"] == 1, res["stats"]
    return res


def _disagg(params, sc, dc, requests, **kw):
    router = DisaggRouter(params, CFG, sc, dc)
    res = router.serve_batch(requests, **kw)
    assert res["stats"]["compiled_signatures_prefill"] == 1, res["stats"]
    assert res["stats"]["compiled_signatures_decode"] == 1, res["stats"]
    return router, res


def _pool_consistent(engine):
    """Engine-lifetime allocator identity once no request is resident:
    every page is either on the free list or held by exactly one radix
    node — a leaked handoff pin or a lost splice ref breaks this."""
    return (
        engine.alloc.num_free + engine.prefix.cached_pages
        == engine.serve_cfg.num_pages
    )


# -- parity ------------------------------------------------------------------
def test_disagg_parity_ragged_stream(params):
    """Staggered ragged arrivals through 1 prefill + 1 decode replica:
    greedy tokens equal the monolithic engine's, every request actually
    migrated (its first decode step ran on the decode replica), and the
    wider prefill budget still compiles once per class."""
    sc = ServingConfig(
        page_size=4, num_pages=32, max_slots=3, pages_per_slot=6,
        token_budget=8, prefill_chunk=4,
        prefix_cache=PrefixCacheConfig(enabled=True),
    )
    reqs = lambda: _reqs(_prompts([5, 11, 3, 7], 30), [0, 0, 2, 4])  # noqa: E731
    base = _mono(params, sc, reqs())
    dc = DisaggConfig(enabled=True, transfer_pages=4, prefill_token_budget=16)
    _, res = _disagg(params, sc, dc, reqs())
    assert res["outputs"] == base["outputs"]
    assert res["stats"]["handoffs"] == 4
    assert res["stats"]["handoff_pages_moved"] >= 4
    assert res["stats"]["transfer_chunks"] >= 1


def test_disagg_parity_decode_side_speculation(params):
    """Decode-class speculation (ngram draft-then-verify) composes with
    the handoff: drafts fire only after migration, acceptance is lossless,
    so tokens still equal the PLAIN monolithic stream's."""
    sc = ServingConfig(
        page_size=4, num_pages=32, max_slots=2, pages_per_slot=8,
        token_budget=8, prefill_chunk=4,
    )
    prompts = _prompts([9, 7], 40)
    reqs = lambda: _reqs(prompts, [0, 2], max_new=8)  # noqa: E731
    base = _mono(params, sc, reqs())
    spec_sc = dataclasses.replace(
        sc, speculative=SpeculativeConfig(enabled=True, draft_len=3),
    )
    dc = DisaggConfig(enabled=True, transfer_pages=2)
    _, res = _disagg(params, spec_sc, dc, reqs())
    assert res["outputs"] == base["outputs"]
    assert res["stats"]["drafted_tokens"] > 0
    assert res["stats"]["handoffs"] == 2


def test_disagg_parity_forced_preemption(params):
    """A pool tight enough to preempt ALREADY-MIGRATED requests: the
    victim requeues on the decode replica (fed reset, pages donated),
    recomputes through the radix tree, and the final tokens still equal
    the monolithic engine's — the half-transferred request edge case."""
    sc = ServingConfig(
        page_size=2, num_pages=8, max_slots=3, pages_per_slot=6,
        token_budget=6, prefill_chunk=3,
        prefix_cache=PrefixCacheConfig(enabled=True),
    )
    reqs = lambda: _reqs(_prompts([4, 4, 4], 20), [0, 0, 0], 8)  # noqa: E731
    base = _mono(params, sc, reqs())
    dc = DisaggConfig(enabled=True, transfer_pages=2)
    router, res = _disagg(params, sc, dc, reqs())
    assert res["outputs"] == base["outputs"]
    assert res["stats"]["preemptions"] >= 1
    # preempted victims re-prefill ON the decode replica (its scheduler
    # requeued them) — they never migrate twice
    assert res["stats"]["handoffs"] == 3
    assert _pool_consistent(router.prefill[0])
    assert _pool_consistent(router.decode[0])


# -- handoff edge cases ------------------------------------------------------
def test_handoff_expires_in_flight_and_releases_pins(params):
    """A handoff racing its deadline: the decode replica's single slot is
    hogged, the victim's prefill finishes and its pinned pages sit in
    flight until the deadline expires them — finish_reason "timed_out",
    and every prefill-side pin is released (no leaked pages)."""
    sc = ServingConfig(
        page_size=4, num_pages=32, max_slots=1, pages_per_slot=8,
        token_budget=8, prefill_chunk=4,
        prefix_cache=PrefixCacheConfig(enabled=True),
    )
    hog = Request(prompt=_prompts([4], 7)[0], max_new_tokens=20, arrival=0)
    victim = Request(
        prompt=_prompts([4], 8)[0], max_new_tokens=4, arrival=1, deadline=8,
    )
    dc = DisaggConfig(enabled=True, transfer_pages=4)
    router, res = _disagg(params, sc, dc, [hog, victim])
    assert victim.finish_reason == "timed_out"
    assert res["stats"]["handoff_expired"] == 1
    assert res["stats"]["timed_out"] == 1
    assert hog.finish_reason == "length"
    assert len(hog.generated) == 20
    assert _pool_consistent(router.prefill[0])
    assert _pool_consistent(router.decode[0])


def test_transferred_pages_splice_against_decode_radix(params):
    """Two requests sharing a long system prompt, far enough apart that
    the first has finished (and donated) on the decode replica before the
    second's handoff arrives: the shared pages SPLICE out of the decode
    tree instead of moving again, refcounts stay consistent, and tokens
    match the monolithic run."""
    rng = np.random.default_rng(3)
    system = [int(t) for t in rng.integers(1, 64, (12,))]
    prompts = [
        system + [int(t) for t in rng.integers(1, 64, (3,))],
        system + [int(t) for t in rng.integers(1, 64, (2,))],
    ]
    sc = ServingConfig(
        page_size=4, num_pages=32, max_slots=2, pages_per_slot=8,
        token_budget=8, prefill_chunk=4,
        prefix_cache=PrefixCacheConfig(enabled=True),
    )
    reqs = lambda: _reqs(prompts, [0, 30], max_new=6)  # noqa: E731
    base = _mono(params, sc, reqs())
    dc = DisaggConfig(enabled=True, transfer_pages=4)
    router, res = _disagg(params, sc, dc, reqs())
    assert res["outputs"] == base["outputs"]
    assert res["stats"]["handoff_pages_spliced"] >= 3  # 12-token system
    assert res["stats"]["sticky_routed"] >= 1
    assert _pool_consistent(router.prefill[0])
    assert _pool_consistent(router.decode[0])


# -- engine-lifetime prefix cache --------------------------------------------
def test_engine_lifetime_cache_across_serve_batch_calls(params):
    """The tentpole's second half: allocator + radix tree survive across
    `serve_batch` calls on one engine. A second batch re-sending the first
    call's system prompt skips >50% of its prefill (zero re-prefill of the
    shared full pages), still matches a cold engine's tokens, and
    `reset_prefix_cache()` restores cold behavior."""
    system = [int(t) for t in np.random.default_rng(5).integers(1, 64, (16,))]

    def mk(seed):
        tail = np.random.default_rng(100 + seed).integers(1, 64, (2,))
        return _reqs([system + [int(t) for t in tail]], [0], max_new=4)
    sc = ServingConfig(
        page_size=4, num_pages=32, max_slots=2, pages_per_slot=8,
        token_budget=8, prefill_chunk=4,
        prefix_cache=PrefixCacheConfig(enabled=True),
    )
    eng = ServingEngine(params, CFG, sc)
    first = eng.serve_batch(mk(0))
    assert first["stats"]["prefill_skipped_tokens"] == 0  # cold tree
    second_reqs = mk(1)
    second = eng.serve_batch(second_reqs)
    skipped = second["stats"]["prefill_skipped_tokens"]
    prompt_len = len(second_reqs[0].prompt)
    assert skipped >= len(system), (skipped, len(system))
    assert skipped / prompt_len > 0.5
    # the shared prefix truly never re-prefilled: only tokens past the
    # cached pages (plus the sampled ones) were ever fed
    assert second["stats"]["tokens_fed"] <= prompt_len - skipped + 1 + 4
    # parity: warm tokens equal a cold engine's on the identical request
    cold = ServingEngine(params, CFG, sc).serve_batch(mk(1))
    assert second["outputs"] == cold["outputs"]
    assert eng.step_cache_size() == 1  # both calls, one signature
    # explicit reset returns the engine to cold
    assert eng.reset_prefix_cache() > 0
    assert eng.alloc.num_free == sc.num_pages
    third = eng.serve_batch(mk(1))
    assert third["stats"]["prefill_skipped_tokens"] == 0
    assert third["outputs"] == cold["outputs"]


def test_engine_lifetime_feeds_disagg_peers(params):
    """Across two DisaggRouter.serve_batch calls the prefill replica's
    radix tree is warm too: the second call's prefill skips the system
    prompt entirely — engine-lifetime caching composes with handoff."""
    rng = np.random.default_rng(9)
    system = [int(t) for t in rng.integers(1, 64, (12,))]
    tail = [int(t) for t in rng.integers(1, 64, (3,))]
    mk = lambda: _reqs([system + tail], [0], max_new=4)  # noqa: E731
    sc = ServingConfig(
        page_size=4, num_pages=32, max_slots=2, pages_per_slot=8,
        token_budget=8, prefill_chunk=4,
        prefix_cache=PrefixCacheConfig(enabled=True),
    )
    router = DisaggRouter(params, CFG, sc, DisaggConfig(enabled=True))
    router.serve_batch(mk())
    res = router.serve_batch(mk())
    assert res["stats"]["prefill_skipped_tokens"] >= len(system) - sc.page_size
    assert res["stats"]["handoffs"] == 1


# -- KVTransfer unit behavior ------------------------------------------------
def _tiny_engine(params, **over):
    geo = dict(page_size=4, num_pages=8, max_slots=2, pages_per_slot=4,
               token_budget=8)
    geo.update(over)
    return ServingEngine(params, CFG, ServingConfig(**geo))


def test_kv_transfer_moves_pages_and_chunks(params):
    src = _tiny_engine(params)
    dst = _tiny_engine(params, num_pages=16)  # num_pages may differ
    # stamp recognizable values into three source pages
    src.pool = jax.tree.map(
        lambda a: a.at[:, 2].set(1.5).at[:, 3].set(2.5).at[:, 5].set(3.5),
        src.pool,
    )
    xfer = KVTransfer(src, dst, batch_pages=2)
    moved = xfer.move([(2, 7), (3, 9), (5, 1)])
    assert moved == 3
    assert xfer.n_pages == 3 and xfer.n_chunks == 2  # 2+1 under batch=2
    for leaf_dst in jax.tree.leaves(dst.pool):
        np.testing.assert_allclose(np.asarray(leaf_dst[:, 7]), 1.5)
        np.testing.assert_allclose(np.asarray(leaf_dst[:, 9]), 2.5)
        np.testing.assert_allclose(np.asarray(leaf_dst[:, 1]), 3.5)
        np.testing.assert_allclose(np.asarray(leaf_dst[:, 0]), 0.0)
    assert xfer.move([]) == 0
    assert xfer.n_chunks == 2


def test_kv_transfer_rejects_mismatched_geometry(params):
    src = _tiny_engine(params)
    with pytest.raises(ValueError, match="page_size"):
        KVTransfer(src, _tiny_engine(params, page_size=8))
    with pytest.raises(ValueError, match="batch_pages"):
        KVTransfer(src, _tiny_engine(params), batch_pages=0)


def test_disagg_config_validation():
    with pytest.raises(ValueError):
        DisaggConfig(prefill_replicas=0)
    with pytest.raises(ValueError):
        DisaggConfig(transfer_pages=0)
    with pytest.raises(ValueError):
        DisaggConfig(prefill_token_budget=0)
