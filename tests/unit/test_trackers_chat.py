"""Tracker bridges, chat dataset, capability CLI."""

import json

import numpy as np
import pytest


def test_null_tracker_jsonl(tmp_path):
    from automodel_tpu.loggers.trackers import _NullTracker

    t = _NullTracker(str(tmp_path), "wandb")
    t.log_config({"lr": 1e-3})
    t.log({"loss": 1.5}, step=1)
    t.finish()
    recs = [json.loads(l) for l in open(tmp_path / "wandb_metrics.jsonl")]
    assert recs[0]["_config"] == {"lr": 1e-3}
    assert recs[1]["loss"] == 1.5 and recs[1]["step"] == 1
    assert recs[-1]["_status"] == "FINISHED"


@pytest.mark.slow
def test_recipe_with_tracker(tmp_path):
    from tests.unit.test_recipe import _smoke_cfg
    from automodel_tpu.cli.app import resolve_recipe_class

    cfg = _smoke_cfg(tmp_path)
    cfg.set("wandb", {"project": "test", "mode": "offline"})
    cfg.set("step_scheduler.max_steps", 2)
    r = resolve_recipe_class(cfg)(cfg)
    r.setup()
    assert len(r.trackers) == 1
    r.run_train_validation_loop()
    # offline wandb either made a real offline run dir or the jsonl mirror
    import glob

    assert glob.glob(str(tmp_path / "wandb*")) or glob.glob(
        str(tmp_path / "wandb_metrics.jsonl")
    )


class StubTokenizer:
    bos_token_id = 1
    eos_token_id = 2
    pad_token_id = 0
    chat_template = None

    def __call__(self, text, add_special_tokens=False):
        # one token per character, offset into "vocab"
        return {"input_ids": [3 + (ord(c) % 50) for c in text]}


def test_chat_dataset_assistant_only_masking(tmp_path):
    from automodel_tpu.datasets.chat import ChatDatasetConfig

    rows = [{"messages": [
        {"role": "user", "content": "hi"},
        {"role": "assistant", "content": "yo"},
    ]}]
    p = tmp_path / "chat.jsonl"
    p.write_text("\n".join(json.dumps(r) for r in rows))
    ds = ChatDatasetConfig(path=str(p), seq_len=64).build(StubTokenizer())
    s = ds[0]
    labels = s["labels"]
    ids = s["input_ids"]
    # some labels supervised (assistant span + eos), some masked (user span)
    assert (labels != -100).sum() > 0
    n_masked = int((labels == -100).sum())
    assert n_masked > 40  # padding + user span
    # supervised labels equal the NEXT input id (shift by one)
    sup = np.flatnonzero(labels[:-1] != -100)
    np.testing.assert_array_equal(labels[sup], ids[sup + 1])


def test_capabilities_cli(capsys):
    from automodel_tpu.cli.app import main

    main(["--capabilities"])
    out = json.loads(capsys.readouterr().out)
    assert "LlamaForCausalLM" in out["architectures"]
    assert "llm_kd" in out["recipes"]
    assert any(p.startswith("pp(") for p in out["parallelism"])


class TemplatedStubTokenizer(StubTokenizer):
    """Template with a one-time preamble — regression for per-message render."""

    chat_template = "PREAMBLE"

    def apply_chat_template(self, messages, tokenize=False, add_generation_prompt=False):
        return "<<SYS>>\n" + "".join(f"[{m['role']}]{m['content']}" for m in messages)


def test_chat_template_preamble_emitted_once(tmp_path):
    from automodel_tpu.datasets.chat import ChatDatasetConfig

    rows = [{"messages": [
        {"role": "user", "content": "ab"},
        {"role": "assistant", "content": "cd"},
        {"role": "user", "content": "ef"},
    ]}]
    p = tmp_path / "chat.jsonl"
    p.write_text("\n".join(json.dumps(r) for r in rows))
    tok = TemplatedStubTokenizer()
    ds = ChatDatasetConfig(path=str(p), seq_len=128).build(tok)
    s = ds[0]
    # total real tokens == full-conversation rendering + eos (preamble once)
    full = tok.apply_chat_template(rows[0]["messages"])
    n_real = len(tok(full)["input_ids"]) + 1
    assert int((s["input_ids"] != 0).sum()) == n_real
    # final user turn → EOS not supervised
    assert s["labels"][n_real - 2] == -100 or s["labels"][n_real - 1] == -100


def test_evaluator_length_mismatch_raises():
    import pytest

    from automodel_tpu.eval.tool_call_evaluator import evaluate_tool_calls

    with pytest.raises(ValueError):
        evaluate_tool_calls(["a", "b"], [[]])


class MergingTokenizer(StubTokenizer):
    """Simulates BPE merging across message boundaries: 'a' followed by the
    template's '\n' junction becomes one merged token id 99."""

    def __call__(self, text, add_special_tokens=False):
        out = []
        i = 0
        while i < len(text):
            if text[i] == "a" and i + 1 < len(text) and text[i + 1] == "\n":
                out.append(99)
                i += 2
            else:
                out.append(3 + (ord(text[i]) % 50))
                i += 1
        return {"input_ids": out}


def test_chat_boundary_merge_resync(tmp_path):
    from automodel_tpu.datasets.chat import ChatDatasetConfig

    rows = [{"messages": [
        {"role": "user", "content": "tea"},       # ends with 'a' → merges
        {"role": "assistant", "content": "ok"},
    ]}]
    p = tmp_path / "chat.jsonl"
    p.write_text(json.dumps(rows[0]))
    tok = MergingTokenizer()
    ds = ChatDatasetConfig(path=str(p), seq_len=64).build(tok)
    s = ds[0]
    # ids must equal the FULL conversation rendering (+eos)
    from automodel_tpu.models.auto_tokenizer import apply_chat_template

    full = tok(apply_chat_template(tok, rows[0]["messages"]))["input_ids"] + [2]
    np.testing.assert_array_equal(s["input_ids"][: len(full)], full)
    assert 99 in s["input_ids"].tolist()  # the merged token survived


def test_length_grouped_dataloader():
    from automodel_tpu.datasets.loader import DataloaderConfig

    class LenDataset:
        lengths = list(range(64, 0, -1))

        def __len__(self):
            return 64

        def __getitem__(self, i):
            return {"input_ids": np.zeros(4, np.int32)}

    dl = DataloaderConfig(microbatch_size=8, length_grouped=True).build(LenDataset())
    list(dl)  # iterates without error
    import pytest

    class NoLenDataset(LenDataset):
        lengths = None

    with pytest.raises(ValueError):
        list(DataloaderConfig(microbatch_size=8, length_grouped=True).build(NoLenDataset()))
