"""Block-diffusion training mask: the leakage invariant and geometry
(reference: diffusion_gemma/attention_mask.py docstring — the strict
block_q > block_kv comparison IS the correctness property)."""

import numpy as np

from automodel_tpu.dllm.block_diffusion import build_block_diffusion_training_mask


def test_leakage_invariant_strict_block_causal():
    """A canvas query must NEVER see the clean encoder column of its own
    block (nor later blocks) — only strictly-earlier response blocks."""
    prefix, resp, block = 4, 8, 4
    enc_len = prefix + resp
    full, _ = build_block_diffusion_training_mask(
        prefix, resp, enc_len, block, batch_size=1
    )
    m = np.asarray(full[0])  # (resp, enc_len + resp)
    for q in range(resp):
        qb = q // block
        for k in range(enc_len):
            rel = k - prefix
            if rel < 0:
                assert m[q, k], "prompt columns always visible"
            elif rel // block < qb:
                assert m[q, k], f"earlier clean block hidden (q={q}, k={k})"
            else:
                # own block's clean column and later: MUST be masked
                assert not m[q, k], f"LEAKAGE at q={q}, k={k}"


def test_canvas_block_diagonal():
    prefix, resp, block = 2, 8, 4
    enc_len = prefix + resp
    full, _ = build_block_diffusion_training_mask(
        prefix, resp, enc_len, block, batch_size=1
    )
    m = np.asarray(full[0])[:, enc_len:]  # canvas columns
    for q in range(resp):
        for k in range(resp):
            assert m[q, k] == (q // block == k // block)


def test_per_example_prefix_and_pad_tail():
    resp, block = 4, 2
    enc_len = 10  # includes tail padding beyond prefix+resp for example 0
    full, _ = build_block_diffusion_training_mask(
        np.asarray([3, 6]), resp, enc_len, block
    )
    m = np.asarray(full)
    # pad tail (enc positions >= prefix+resp) never attendable
    assert not m[0, :, 3 + resp:enc_len].any()
    assert not m[1, :, 6 + resp:enc_len].any()
    # example-specific prompts fully visible
    assert m[0, :, :3].all() and m[1, :, :6].all()


def test_sliding_window_block_anchored():
    """The encoder window anchors to the block's cache boundary, constant
    for every query in the block (not a per-query band)."""
    prefix, resp, block, sw = 6, 8, 4, 4
    enc_len = prefix + resp
    full, sliding = build_block_diffusion_training_mask(
        prefix, resp, enc_len, block, sliding_window=sw, batch_size=1
    )
    f = np.asarray(full[0])
    s = np.asarray(sliding[0])
    # canvas columns unaffected by the window
    np.testing.assert_array_equal(s[:, enc_len:], f[:, enc_len:])
    for q in range(resp):
        qb = q // block
        cache_end = prefix + qb * block  # exclusive upper from M_OBC
        lo = cache_end - sw + 1
        for k in range(enc_len):
            expect = f[q, k] and (k >= lo)
            assert s[q, k] == expect, (q, k)
        # every query in the same block sees the SAME encoder window
        if q % block:
            np.testing.assert_array_equal(s[q, :enc_len], s[q - 1, :enc_len])
