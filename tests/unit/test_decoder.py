import jax
import jax.numpy as jnp
import numpy as np
import pytest

from automodel_tpu.distributed import MeshConfig
from automodel_tpu.models.llm import decoder
from automodel_tpu.models.llm.decoder import TransformerConfig
from automodel_tpu.models.registry import get_model_spec
from automodel_tpu.parallel import logical_to_shardings

TINY = TransformerConfig(
    vocab_size=128,
    hidden_size=32,
    intermediate_size=64,
    num_layers=2,
    num_heads=4,
    num_kv_heads=2,
    max_position_embeddings=64,
    dtype=jnp.float32,
    remat_policy="none",
)


def test_init_and_forward_shapes():
    params = decoder.init(TINY, jax.random.key(0))
    ids = jnp.zeros((2, 16), jnp.int32)
    logits = decoder.forward(params, TINY, ids)
    assert logits.shape == (2, 16, 128)
    assert logits.dtype == jnp.float32
    hidden = decoder.forward(params, TINY, ids, return_hidden=True)
    assert hidden.shape == (2, 16, 32)


def test_param_specs_tree_matches_params():
    params = decoder.init(TINY, jax.random.key(0))
    specs = decoder.param_specs(TINY)
    # same tree structure
    jax.tree.map(lambda p, s: None, params, specs, is_leaf=lambda x: isinstance(x, tuple))
    flat_p = jax.tree.leaves(params)
    flat_s = jax.tree.leaves(specs, is_leaf=lambda x: isinstance(x, tuple))
    assert len(flat_p) == len(flat_s)
    for p, s in zip(flat_p, flat_s):
        assert p.ndim == len(s), f"{p.shape} vs {s}"


def test_causality():
    """Changing a future token must not affect earlier logits."""
    params = decoder.init(TINY, jax.random.key(1))
    ids1 = jnp.array([[1, 2, 3, 4, 5, 6, 7, 8]], jnp.int32)
    ids2 = ids1.at[0, 6].set(99)
    l1 = decoder.forward(params, TINY, ids1)
    l2 = decoder.forward(params, TINY, ids2)
    np.testing.assert_allclose(l1[0, :6], l2[0, :6], rtol=2e-5, atol=2e-5)
    assert not np.allclose(l1[0, 6:], l2[0, 6:])


def test_segment_ids_isolate_documents():
    """Packed sequences: doc 2 must be unaffected by doc 1's contents."""
    params = decoder.init(TINY, jax.random.key(2))
    seg = jnp.array([[0, 0, 0, 0, 1, 1, 1, 1]], jnp.int32)
    pos = jnp.array([[0, 1, 2, 3, 0, 1, 2, 3]], jnp.int32)
    ids1 = jnp.array([[1, 2, 3, 4, 10, 11, 12, 13]], jnp.int32)
    ids2 = jnp.array([[5, 6, 7, 8, 10, 11, 12, 13]], jnp.int32)
    l1 = decoder.forward(params, TINY, ids1, positions=pos, segment_ids=seg)
    l2 = decoder.forward(params, TINY, ids2, positions=pos, segment_ids=seg)
    np.testing.assert_allclose(l1[0, 4:], l2[0, 4:], rtol=2e-5, atol=2e-5)


@pytest.mark.slow
def test_feature_variants_forward():
    for kw in (
        dict(attention_bias=True),
        dict(qk_norm=True),
        dict(tie_word_embeddings=True),
        dict(sliding_window=4),
        dict(sliding_window=4, layer_types=("sliding", "global")),
        dict(logits_soft_cap=30.0, attn_soft_cap=50.0),
        dict(zero_centered_norm=True, embed_scale=5.65, use_post_norms=True),
        dict(attn_scale=0.25),
    ):
        cfg = TransformerConfig(**{**TINY.__dict__, **kw})
        params = decoder.init(cfg, jax.random.key(3))
        out = decoder.forward(params, cfg, jnp.zeros((1, 8), jnp.int32))
        assert np.isfinite(np.asarray(out)).all(), kw


def test_registry_from_hf():
    hf = {
        "architectures": ["Qwen2ForCausalLM"],
        "vocab_size": 128,
        "hidden_size": 32,
        "intermediate_size": 64,
        "num_hidden_layers": 2,
        "num_attention_heads": 4,
        "num_key_value_heads": 2,
    }
    spec = get_model_spec(hf)
    cfg = spec.config_from_hf(hf, dtype=jnp.float32, remat_policy="none")
    assert cfg.attention_bias  # qwen2 uses qkv bias
    params = spec.module.init(cfg, jax.random.key(0))
    out = spec.module.forward(params, cfg, jnp.zeros((1, 4), jnp.int32))
    assert out.shape == (1, 4, 128)


def test_sharded_forward_matches_single_device():
    ctx = MeshConfig(dp_shard=2, tp=2, cp=2).build()
    params = decoder.init(TINY, jax.random.key(0))
    shardings = logical_to_shardings(
        decoder.param_specs(TINY), ctx, shapes=jax.tree.map(lambda p: p.shape, params)
    )
    sharded = jax.device_put(params, shardings)
    ids = jax.random.randint(jax.random.key(5), (4, 16), 0, 128)
    ref = decoder.forward(params, TINY, ids)

    @jax.jit
    def f(p, i):
        return decoder.forward(p, TINY, i, mesh_ctx=ctx)

    ids_sharded = jax.device_put(ids, ctx.sharding("batch", "cp"))
    out = f(sharded, ids_sharded)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out), rtol=2e-4, atol=2e-4)


def test_per_layer_sliding_windows_differ_from_global():
    """A 'global' layer in the pattern must see beyond the window."""
    base = dict(TINY.__dict__)
    cfg_all = TransformerConfig(**{**base, "sliding_window": 2})
    cfg_mix = TransformerConfig(
        **{**base, "sliding_window": 2, "layer_types": ("sliding", "global")}
    )
    params = decoder.init(cfg_all, jax.random.key(4))
    ids = jnp.arange(12, dtype=jnp.int32)[None, :] % 64
    l_all = decoder.forward(params, cfg_all, ids)
    l_mix = decoder.forward(params, cfg_mix, ids)
    assert not np.allclose(np.asarray(l_all), np.asarray(l_mix))


def test_gemma2_adapter():
    from automodel_tpu.models.llm.families import gemma2_config

    hf = {
        "vocab_size": 128, "hidden_size": 32, "intermediate_size": 64,
        "num_hidden_layers": 4, "num_attention_heads": 4, "num_key_value_heads": 2,
        "head_dim": 8, "query_pre_attn_scalar": 16, "sliding_window": 4,
        "final_logit_softcapping": 30.0, "attn_logit_softcapping": 50.0,
    }
    cfg = gemma2_config(hf, dtype=jnp.float32, remat_policy="none")
    assert cfg.tie_word_embeddings  # gemma default
    assert cfg.use_post_norms and cfg.zero_centered_norm
    assert cfg.attn_scale == pytest.approx(16 ** -0.5)
    assert cfg.layer_types == ("sliding", "global", "sliding", "global")
    params = decoder.init(cfg, jax.random.key(0))
    assert "lm_head" not in params
    assert "post_mlp_norm" in params["layers"]
    out = decoder.forward(params, cfg, jnp.zeros((1, 8), jnp.int32))
    assert np.isfinite(np.asarray(out)).all()


def test_window_plan_paths():
    from automodel_tpu.models.common.layers import window_plan

    assert window_plan((4, 4, 4)) == ("uniform", 4)
    assert window_plan((4, None, 4, None)) == ("periodic", 2, (4, None))
    kind, segs = window_plan((None, None, 4, 4, 4))
    assert kind == "segments" and segs == [(0, 2, None), (2, 5, 4)]


def test_qwen2_swa_segments_forward():
    """max_window_layers split: first layer global, second sliding."""
    from automodel_tpu.models.llm.families import qwen2_config

    hf = {
        "vocab_size": 64, "hidden_size": 32, "intermediate_size": 64,
        "num_hidden_layers": 2, "num_attention_heads": 4, "num_key_value_heads": 2,
        "use_sliding_window": True, "sliding_window": 4, "max_window_layers": 1,
    }
    cfg = qwen2_config(hf, dtype=jnp.float32, remat_policy="none")
    assert cfg.layer_types == ("global", "sliding")
    params = decoder.init(cfg, jax.random.key(0))
    out = decoder.forward(params, cfg, jnp.arange(12, dtype=jnp.int32)[None, :] % 64)
    assert np.isfinite(np.asarray(out)).all()
