"""HLO collective-count guards: CPU-verifiable perf regression fences.

The TPU tunnel has produced zero on-accelerator evidence in five rounds, so
these guards pin the COMPILED collective structure of the headline parallel
programs instead: `jit(...).lower().compile()` on a virtual CPU mesh emits
the same logical collectives GSPMD/shard_map would emit for TPU, and a
change that, say, re-gathers expert weights per microbatch or breaks the
manual-A2A EP dispatch shows up as a count jump here — failing tier-1 with
no accelerator in the loop.

Budgets are pinned to the measured counts of the current lowering (exact,
not fuzzed): a regression that doubles a collective fails loudly; an
optimization that LOWERS a count should consciously re-pin the budget.
Floors assert the collectives that must exist (the ring ppermute, the EP
all-to-all) so the guard also catches silently-degenerate programs."""

import dataclasses
import re

import jax
import jax.numpy as jnp
import pytest

from automodel_tpu.distributed import MeshConfig
from automodel_tpu.loss import fused_linear_cross_entropy
from automodel_tpu.models.llm import decoder
from automodel_tpu.models.llm.decoder import TransformerConfig
from automodel_tpu.models.moe_lm import decoder as moe_decoder
from automodel_tpu.models.moe_lm.decoder import MoETransformerConfig
from automodel_tpu.moe import MoEConfig
from automodel_tpu.parallel import logical_to_shardings

COLLECTIVES = (
    "all-gather", "all-reduce", "reduce-scatter",
    "collective-permute", "all-to-all", "ragged-all-to-all",
)

DENSE = TransformerConfig(
    vocab_size=64, hidden_size=32, intermediate_size=48, num_layers=2,
    num_heads=4, num_kv_heads=2, dtype=jnp.float32, remat_policy="none",
    pipeline_microbatches=2,
)
MOE = MoETransformerConfig(
    vocab_size=64, hidden_size=32, intermediate_size=48, num_layers=2,
    num_heads=4, num_kv_heads=2, first_k_dense=0,
    moe=MoEConfig(
        n_routed_experts=4, n_shared_experts=1, experts_per_token=2,
        moe_intermediate_size=16, shared_expert_intermediate_size=16,
        aux_loss_coeff=0.01, dispatcher="dropless",
    ),
    dtype=jnp.float32, remat_policy="none", pipeline_microbatches=2,
)


def _collective_counts(compiled) -> dict:
    """Count collective instructions in optimized HLO. Scan bodies compile
    once, so counts reflect program structure, not trip counts."""
    txt = compiled.as_text()
    # (?<![\w-]) keeps "all-to-all(" from also matching inside
    # "ragged-all-to-all(" — \b holds after a hyphen
    return {
        c: len(re.findall(rf"(?<![\w-]){c}(?:-start)?\(", txt))
        for c in COLLECTIVES
    }


def _sharded(cfg, mod, ctx):
    params = mod.init(cfg, jax.random.key(0))
    sh = logical_to_shardings(
        mod.param_specs(cfg), ctx,
        shapes=jax.tree.map(lambda p: p.shape, params),
    )
    return jax.device_put(params, sh)


def _ids(ctx, B=8, S=16, seq_axis=None):
    return jax.device_put(
        jnp.zeros((B, S), jnp.int32), ctx.sharding("batch", seq_axis)
    )


def _check(counts: dict, budget: dict, floors: dict = ()):
    for c, limit in budget.items():
        assert counts[c] <= limit, (
            f"{c}: {counts[c]} > pinned budget {limit} — the compiled "
            f"program grew collectives (full counts: {counts}); if this is "
            "an intentional lowering change, re-pin the budget"
        )
    for c, lo in dict(floors).items():
        assert counts[c] >= lo, (
            f"{c}: {counts[c]} < floor {lo} — the program lost a collective "
            f"it needs (degenerate lowering? full counts: {counts})"
        )


def test_hlo_guard_fsdp_grad():
    """dp_shard=8 dense decoder grad: per-layer-scan param all-gathers +
    grad all-reduces; no permutes / A2As may appear in pure FSDP."""
    ctx = MeshConfig(dp_shard=8).build()
    p = _sharded(DENSE, decoder, ctx)
    ids, lab = _ids(ctx), _ids(ctx)

    def loss(p, i, l):
        h = decoder.forward(p, DENSE, i, mesh_ctx=ctx, return_hidden=True)
        ce, _ = fused_linear_cross_entropy(
            h, p["lm_head"]["kernel"], l, chunk_size=64
        )
        return ce

    counts = _collective_counts(
        jax.jit(jax.grad(loss)).lower(p, ids, lab).compile()
    )
    _check(
        counts,
        budget={"all-gather": 18, "all-reduce": 12, "collective-permute": 0,
                "all-to-all": 0, "ragged-all-to-all": 0},
        floors={"all-gather": 1, "all-reduce": 1},
    )


def test_hlo_guard_ring_cp_forward():
    """cp=2 ring attention: the KV ring is collective-permutes (one hop per
    cp peer per scanned attention call), never an A2A."""
    ctx = MeshConfig(cp=2, dp_shard=4).build()
    p = _sharded(DENSE, decoder, ctx)
    ids = _ids(ctx, B=4, seq_axis="cp")
    counts = _collective_counts(
        jax.jit(lambda p, i: decoder.forward(p, DENSE, i, mesh_ctx=ctx))
        .lower(p, ids).compile()
    )
    _check(
        counts,
        budget={"all-gather": 9, "all-reduce": 0, "collective-permute": 4,
                "all-to-all": 0, "ragged-all-to-all": 0},
        floors={"collective-permute": 1},
    )


def test_hlo_guard_ep_moe_forward():
    """ep=4 dropless MoE forward: the manual EP dispatch is a bounded
    number of (dense-bucket, on CPU) all-to-alls — token sort + send +
    return combine; a re-gather of expert weights would spike all-gather."""
    ctx = MeshConfig(ep=4, dp_shard=2).build()
    p = _sharded(MOE, moe_decoder, ctx)
    ids = _ids(ctx)
    counts = _collective_counts(
        jax.jit(lambda p, i: moe_decoder.forward(p, MOE, i, mesh_ctx=ctx))
        .lower(p, ids).compile()
    )
    _check(
        counts,
        budget={"all-gather": 14, "all-reduce": 2, "collective-permute": 0,
                "all-to-all": 3, "ragged-all-to-all": 0},
        floors={"all-to-all": 1},
    )


def test_hlo_guard_paged_decode_step():
    """The serving engine's jitted step: per-layer paged-pool reads must
    stay GATHERS (page-table indexed; a regression to per-request dense
    caches would spike dynamic-slice / blow the gather count), pool writes
    stay O(stacks) in-place updates, and a single-process step must emit NO
    collectives. The prefix-hit path rides the SAME program — cross-request
    page sharing is pure page-table indirection — plus the fixed-shape
    copy-on-write block (cow_src/cow_dst, one bounded page copy per slot),
    whose cost is pinned into the budgets below. Counts are per compiled
    program structure (the layer scan compiles once), pinned exactly like
    the budgets above."""
    from automodel_tpu.serving.engine import ServingConfig, ServingEngine

    cfg = dataclasses.replace(DENSE, pipeline_microbatches=1)
    params = decoder.init(cfg, jax.random.key(0))
    eng = ServingEngine(params, cfg, ServingConfig(
        page_size=4, num_pages=16, max_slots=2, pages_per_slot=4,
        token_budget=8,
    ))
    T, S, P = 8, 2, 4
    batch = {k: jnp.zeros(T, jnp.int32) for k in ("tok", "slot", "pos", "page", "off")}
    batch.update(
        page_tables=jnp.zeros((S, P), jnp.int32),
        sample_tok=jnp.zeros(S, jnp.int32),
        temp=jnp.zeros(S, jnp.float32),
        seed=jnp.zeros(S, jnp.int32),
        cow_src=jnp.zeros(S, jnp.int32),
        cow_dst=jnp.zeros(S, jnp.int32),
    )
    compiled = eng._step.lower(eng.params, eng.pool, batch).compile()
    txt = compiled.as_text()
    ops = ("gather", "dynamic-slice", "dynamic-update-slice") + COLLECTIVES
    counts = {
        c: len(re.findall(rf"= (?:[\w\[\],<>:{{}} ]+ )?{c}\(", txt))
        for c in ops
    }
    # re-pinned for the COW block: +2 gathers (read cow_src pages of k and
    # v), +8 slice/update pairs scattering them to cow_dst — still O(pool
    # leaves), independent of traffic, and collective-free
    _check(
        counts,
        budget={"gather": 9, "dynamic-slice": 27, "dynamic-update-slice": 6,
                "all-gather": 0, "all-reduce": 0, "collective-permute": 0,
                "all-to-all": 0, "ragged-all-to-all": 0},
        floors={"gather": 2},  # ≥ the paged k/v page gathers
    )


def test_hlo_guard_pp_ep_1f1b_grad():
    """The flagship PP×EP program: explicit 1F1B grad with the expert A2A
    inside each stage's step. The ppermute ring (fwd + bwd streams) and the
    per-stage A2As (fwd, recompute, dgrad) are the pinned structure; expert
    weights must NOT be re-gathered per microbatch (all-gather budget)."""
    cfg = dataclasses.replace(MOE, pipeline_schedule="1f1b")
    ctx = MeshConfig(pp=2, ep=2, dp_shard=2).build()
    p = _sharded(cfg, moe_decoder, ctx)
    batch = {"input_ids": _ids(ctx), "labels": _ids(ctx)}
    grad_fn = decoder.make_pp_1f1b_loss_and_grad(cfg, ctx, chunk_size=64)
    counts = _collective_counts(
        jax.jit(grad_fn).lower(p, batch, jax.random.key(0)).compile()
    )
    _check(
        counts,
        budget={"all-gather": 13, "all-reduce": 24, "collective-permute": 6,
                "all-to-all": 11, "ragged-all-to-all": 0},
        floors={"collective-permute": 2, "all-to-all": 2},
    )
