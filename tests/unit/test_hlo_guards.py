"""HLO baseline guards: CPU-verifiable perf regression fences.

The TPU tunnel has produced zero on-accelerator evidence, so these guards
pin the COMPILED structure of the headline parallel programs instead:
`jit(...).lower().compile()` on a virtual CPU mesh emits the same logical
collectives GSPMD/shard_map would emit for TPU, and a change that, say,
re-gathers expert weights per microbatch or breaks the manual-A2A EP
dispatch shows up as baseline drift here — failing tier-1 with no
accelerator in the loop.

This file used to hand-count `compiled.as_text()` ops with five copies of
a regex; it is now a thin shell over `automodel_tpu.analysis`: one builder
per jitted entry point (analysis/entrypoints.py), one structured report
per compiled program (analysis/hlo.py), and one checked-in JSON baseline
per entry (analysis/baselines/*.json). The ratchet is two-sided: a
regression that GROWS a collective fails, and an optimization that LOWERS
a count also fails until the baseline is consciously re-pinned with

    python -m automodel_tpu.analysis --update-baselines

which replaces hand-editing counts in five tests. The same comparisons run
in CI via `python -m automodel_tpu.analysis`; keeping them as individual
tier-1 tests too gives per-entry failure granularity and rides the
existing pytest budget."""

import os

import pytest

import automodel_tpu.analysis
from automodel_tpu.analysis import compare_report, load_baseline
from automodel_tpu.analysis.entrypoints import (
    ENTRY_POINTS,
    STRUCTURAL_INVARIANTS,
    build_report,
    check_invariants,
)

# the SAME directory `python -m automodel_tpu.analysis` gates
BASELINES = os.path.join(
    os.path.dirname(os.path.abspath(automodel_tpu.analysis.__file__)),
    "baselines",
)


@pytest.mark.parametrize("entry", sorted(ENTRY_POINTS))
def test_hlo_baseline(entry):
    report = build_report(entry)
    baseline = load_baseline(BASELINES, entry)
    assert baseline is not None, (
        f"no baseline for {entry!r} in {BASELINES} — run "
        "`python -m automodel_tpu.analysis --update-baselines`"
    )
    drifts = compare_report(report, baseline)
    assert not drifts, (
        "compiled program drifted from its baseline; if intentional, "
        "re-pin with `python -m automodel_tpu.analysis --update-baselines` "
        "and justify in the PR:\n" + "\n".join(drifts)
    )
    # structural invariants (floors / zero-ceilings / op floors) live next
    # to the entry-point registry so the CLI gate enforces the SAME tables
    # — and --update-baselines refuses to pin a program that violates them
    assert check_invariants(report) == []
    assert entry in STRUCTURAL_INVARIANTS  # registry/invariants stay in sync


@pytest.mark.parametrize("entry", [
    "paged_serve_step", "spec_serve_step", "prefill_step", "kv_transfer",
])
def test_serve_step_donation_pinned(entry):
    """The serve step's pool donation is part of the compiled contract:
    losing it silently doubles pool memory — in the plain, speculative
    draft-then-verify, and prefill-class step programs alike, and in the
    handoff's fused page-copy program (whose destination pool is donated
    so a transfer never double-buffers). The aliasing table in
    the baseline must stay non-empty (belt to the baseline's suspenders —
    this asserts the INVARIANT, not a count that drifts)."""
    baseline = load_baseline(BASELINES, entry)
    assert baseline is not None
    assert baseline.donation, (
        f"{entry} baseline has an empty input_output_alias table — "
        "the pool donation was lost"
    )
