"""Kimi-VL: MoonViT tower + projector + DeepSeek-V3 MoE text."""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from automodel_tpu.models.registry import get_model_spec
from automodel_tpu.models.vlm import kimi_vl

KIMI_HF = {
    "architectures": ["KimiVLForConditionalGeneration"],
    "model_type": "kimi_vl",
    "media_placeholder_token_id": 120,
    "vision_config": {
        "patch_size": 14, "init_pos_emb_height": 8, "init_pos_emb_width": 8,
        "num_attention_heads": 2, "num_hidden_layers": 2,
        "hidden_size": 32, "intermediate_size": 48,
        "merge_kernel_size": [2, 2],
    },
    "text_config": {
        "vocab_size": 128, "hidden_size": 32, "intermediate_size": 64,
        "num_hidden_layers": 2, "num_attention_heads": 4,
        "num_key_value_heads": 4,
        "n_routed_experts": 4, "n_shared_experts": 1,
        "num_experts_per_tok": 2, "moe_intermediate_size": 16,
        "first_k_dense_replace": 1, "norm_topk_prob": True,
        "kv_lora_rank": 16, "q_lora_rank": 12,
        "qk_nope_head_dim": 8, "qk_rope_head_dim": 8, "v_head_dim": 8,
    },
}


def _setup():
    spec = get_model_spec(KIMI_HF)
    cfg = spec.config_from_hf(KIMI_HF, dtype=jnp.float32, remat_policy="none")
    params = kimi_vl.init(cfg, jax.random.key(0))
    return spec, cfg, params


def _mock_batch(cfg, B=2, S=32, img=56):
    # (img/14)² = 16 patches → /4 merge = 4 image tokens
    n_img = (img // cfg.vision.patch_size // 2) ** 2
    rng = np.random.default_rng(0)
    text = rng.integers(1, 100, (B, S - n_img), dtype=np.int32)
    ids = np.concatenate(
        [np.full((B, n_img), cfg.image_token_id, np.int32), text], axis=1
    )
    pixels = rng.normal(size=(B, img, img, 3)).astype(np.float32)
    return jnp.asarray(ids), jnp.asarray(pixels)


@pytest.mark.slow
def test_kimi_vl_forward_moe_protocol():
    spec, cfg, params = _setup()
    ids, pixels = _mock_batch(cfg)
    hidden, aux, stats = kimi_vl.forward(
        params, cfg, ids, pixels, return_hidden=True, return_stats=True
    )
    assert hidden.shape == (2, 32, 32)
    assert np.isfinite(np.asarray(hidden)).all()
    assert stats["tokens_per_expert"].shape == (1, 4)  # 1 moe layer, 4 experts

    # the image embedding path is live: different pixels → different hidden
    h2, _, _ = kimi_vl.forward(
        params, cfg, ids, pixels * 0.0, return_hidden=True, return_stats=True
    )
    assert np.abs(np.asarray(hidden) - np.asarray(h2)).max() > 1e-4


@pytest.mark.slow
def test_kimi_vl_adapter_roundtrip():
    from automodel_tpu.checkpoint.hf_adapter import get_adapter

    spec, cfg, params = _setup()
    ad = get_adapter(spec.adapter_name, cfg, **spec.adapter_kwargs)
    sd = dict(ad.to_hf(params))
    assert "vision_tower.encoder.blocks.0.wqkv.weight" in sd
    assert sd["vision_tower.patch_embed.proj.weight"].shape == (32, 3, 14, 14)
    assert "multi_modal_projector.linear_2.weight" in sd
    assert "language_model.model.layers.0.self_attn.kv_b_proj.weight" in sd
    assert "language_model.lm_head.weight" in sd
    p2 = ad.from_hf(lambda k: np.asarray(sd[k]))
    ids, pixels = _mock_batch(cfg)
    o1, _, _ = kimi_vl.forward(params, cfg, ids, pixels, return_stats=True)
    o2, _, _ = kimi_vl.forward(
        jax.tree.map(jnp.asarray, p2), cfg, ids, pixels, return_stats=True
    )
    np.testing.assert_allclose(np.asarray(o1), np.asarray(o2), atol=1e-5)


@pytest.mark.recipe
def test_kimi_vl_recipe_trains(tmp_path):
    from automodel_tpu.cli.app import resolve_recipe_class
    from automodel_tpu.config import ConfigNode

    cfg = ConfigNode({
        "seed": 7,
        "run_dir": str(tmp_path),
        "auto_resume": False,
        "recipe": "vlm_finetune",
        "model": {"hf_config": KIMI_HF, "dtype": "float32", "remat_policy": "none"},
        "distributed": {"dp_shard": -1, "ep": 2},
        "dataset": {
            "_target_": "automodel_tpu.datasets.vlm.MockVLMDatasetConfig",
            "num_samples": 32, "seq_len": 32, "vocab_size": 128,
            "image_size": 56, "patch_size": 14, "merge_factor": 2,
            "image_token_id": 120,
        },
        "dataloader": {"microbatch_size": 8, "grad_acc_steps": 1},
        "optimizer": {"name": "adamw", "lr": 1e-3},
        "lr_scheduler": {"style": "constant", "warmup_steps": 0},
        "step_scheduler": {"max_steps": 3, "ckpt_every_steps": 100},
        "checkpoint": {"enabled": False},
        "loss": {"chunk_size": 64},
        "freeze_vision_tower": True,
    })
    r = resolve_recipe_class(cfg)(cfg)
    r.setup()
    assert r.is_moe
    r.run_train_validation_loop()
    recs = [json.loads(l) for l in open(tmp_path / "training.jsonl") if l.strip()]
    assert len(recs) == 3
    assert all(np.isfinite(x["loss"]) for x in recs)
    assert "moe_load_imbalance" in recs[-1]


@pytest.mark.recipe
@pytest.mark.slow  # KD over two MoE models: heaviest compile in the file
def test_kimi_vl_kd_moe_student_and_teacher(tmp_path):
    """VLM KD with MoE student AND teacher (both kimi-vl): the tuple-return
    teacher path and the gate-bias stats must both flow."""
    from automodel_tpu.cli.app import resolve_recipe_class
    from automodel_tpu.config import ConfigNode

    cfg = ConfigNode({
        "seed": 7,
        "run_dir": str(tmp_path),
        "auto_resume": False,
        "recipe": "vlm_kd",
        "model": {"hf_config": KIMI_HF, "dtype": "float32", "remat_policy": "none"},
        "teacher_model": {"hf_config": KIMI_HF, "dtype": "float32", "remat_policy": "none"},
        "kd": {"ratio": 0.5, "temperature": 2.0},
        "distributed": {"dp_shard": -1},
        "dataset": {
            "_target_": "automodel_tpu.datasets.vlm.MockVLMDatasetConfig",
            "num_samples": 16, "seq_len": 32, "vocab_size": 128,
            "image_size": 56, "patch_size": 14, "merge_factor": 2,
            "image_token_id": 120,
        },
        "dataloader": {"microbatch_size": 8, "grad_acc_steps": 1},
        "optimizer": {"name": "adamw", "lr": 1e-3},
        "lr_scheduler": {"style": "constant", "warmup_steps": 0},
        "step_scheduler": {"max_steps": 2, "ckpt_every_steps": 100},
        "checkpoint": {"enabled": False},
        "loss": {"chunk_size": 64},
    })
    r = resolve_recipe_class(cfg)(cfg)
    r.setup()
    r.run_train_validation_loop()
    recs = [json.loads(l) for l in open(tmp_path / "training.jsonl") if l.strip()]
    assert len(recs) == 2
    assert all(np.isfinite(x["loss"]) for x in recs)


@pytest.mark.slow
def test_kimi_vl_generate_conditions_on_image():
    """vlm_generate: image-conditioned decode runs and the image changes
    the continuation (greedy, tiny model)."""
    from automodel_tpu.inference.generate import GenerateConfig, vlm_generate

    spec, cfg, params = _setup()
    ids, pixels = _mock_batch(cfg, B=1, S=16, img=56)
    out1 = vlm_generate(
        kimi_vl, params, cfg, ids, pixels, jax.random.key(0),
        GenerateConfig(max_new_tokens=6),
    )
    assert out1.shape == (1, 22)
    out2 = vlm_generate(
        kimi_vl, params, cfg, ids, pixels * 3.0, jax.random.key(0),
        GenerateConfig(max_new_tokens=6),
    )
    assert not np.array_equal(np.asarray(out1), np.asarray(out2))


@pytest.mark.slow
def test_kimi_k25_vl_variant():
    """K2.5: temporal t=0 sincos constant live; mm_projector.proj.{0,2}
    checkpoint naming round-trips (reference: kimi_k25_vl/
    state_dict_adapter.py:208)."""
    import dataclasses

    from automodel_tpu.checkpoint.hf_adapter import get_adapter
    from automodel_tpu.models.registry import get_model_spec

    hf = dict(KIMI_HF, architectures=["KimiK25VLForConditionalGeneration"])
    spec = get_model_spec(hf)
    cfg = spec.config_from_hf(hf, dtype=jnp.float32, remat_policy="none")
    assert cfg.vision.temporal_pos_emb
    params = kimi_vl.init(cfg, jax.random.key(0))

    # the t=0 temporal constant changes the tower output vs the plain tower
    cfg_plain = dataclasses.replace(
        cfg, vision=dataclasses.replace(cfg.vision, temporal_pos_emb=False)
    )
    rng = np.random.default_rng(0)
    pix = jnp.asarray(rng.normal(size=(1, 56, 56, 3)).astype(np.float32))
    f1 = kimi_vl.encode_images(params, cfg, pix)
    f2 = kimi_vl.encode_images(params, cfg_plain, pix)
    assert np.abs(np.asarray(f1) - np.asarray(f2)).max() > 1e-6

    ad = get_adapter(spec.adapter_name, cfg, **spec.adapter_kwargs)
    sd = dict(ad.to_hf(params))
    assert "mm_projector.proj.0.weight" in sd
    assert "mm_projector.proj.2.bias" in sd
    assert "mm_projector.pre_norm.weight" in sd
    assert not any(k.startswith("multi_modal_projector.") for k in sd)
    p2 = ad.from_hf(lambda k: np.asarray(sd[k]))
    ids = jnp.asarray(
        np.concatenate([np.full((1, 4), 120), rng.integers(1, 100, (1, 8))], 1),
        jnp.int32,
    )
    o1 = kimi_vl.forward(params, cfg, ids, pix)
    o2 = kimi_vl.forward(jax.tree.map(jnp.asarray, p2), cfg, ids, pix)
    for a, b in zip(jax.tree.leaves(o1), jax.tree.leaves(o2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)
