import jax
import jax.numpy as jnp
import numpy as np
import pytest

from automodel_tpu.distributed import MeshConfig
from automodel_tpu.loss import fused_linear_cross_entropy
from automodel_tpu.models.common.layers import cast_params
from automodel_tpu.models.llm import decoder
from automodel_tpu.models.llm.decoder import TransformerConfig
from automodel_tpu.optim import LRSchedulerConfig, OptimizerConfig
from automodel_tpu.parallel import logical_to_shardings
from automodel_tpu.training import TrainStepConfig, init_train_state, make_train_step

CFG = TransformerConfig(
    vocab_size=64,
    hidden_size=32,
    intermediate_size=64,
    num_layers=2,
    num_heads=4,
    num_kv_heads=4,
    dtype=jnp.float32,
    remat_policy="full",
)


def _loss_fn(params, batch, rng):
    hidden = decoder.forward(params, CFG, batch["input_ids"], return_hidden=True)
    kernel = params["lm_head"]["kernel"]
    return fused_linear_cross_entropy(hidden, kernel, batch["labels"], chunk_size=32)


def _make_batch(key, accum, mb, seq):
    ids = jax.random.randint(key, (accum, mb, seq + 1), 0, 64)
    return {"input_ids": ids[..., :-1], "labels": ids[..., 1:]}


@pytest.mark.slow
def test_train_loss_decreases_memorization():
    params = decoder.init(CFG, jax.random.key(0))
    sched = LRSchedulerConfig(warmup_steps=2, decay_steps=100, style="constant").build(1e-2)
    tx = OptimizerConfig(lr=1e-2, weight_decay=0.0).build(sched)
    state = init_train_state(params, tx)
    step = jax.jit(make_train_step(_loss_fn, tx, sched, TrainStepConfig(max_grad_norm=1.0)), donate_argnums=0)
    batch = _make_batch(jax.random.key(1), 2, 2, 16)  # fixed batch → memorize
    losses = []
    for i in range(30):
        state, metrics = step(state, batch, jax.random.key(i))
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0] * 0.5, losses
    assert int(state.step) == 30
    assert np.isfinite(losses).all()


@pytest.mark.slow
def test_grad_accum_invariance():
    """2 microbatches of 2 == 1 microbatch of 4 (same tokens)."""
    params = decoder.init(CFG, jax.random.key(0))
    tx = OptimizerConfig(lr=1e-3, weight_decay=0.0).build()
    ids = jax.random.randint(jax.random.key(7), (4, 17), 0, 64)
    b1 = {"input_ids": ids[None, :, :-1], "labels": ids[None, :, 1:]}
    b2 = {"input_ids": ids.reshape(2, 2, 17)[..., :-1], "labels": ids.reshape(2, 2, 17)[..., 1:]}
    step = jax.jit(make_train_step(_loss_fn, tx))
    s1, m1 = step(init_train_state(params, tx), b1, jax.random.key(0))
    s2, m2 = step(init_train_state(params, tx), b2, jax.random.key(0))
    np.testing.assert_allclose(float(m1["loss"]), float(m2["loss"]), rtol=1e-5)
    np.testing.assert_allclose(float(m1["grad_norm"]), float(m2["grad_norm"]), rtol=1e-5)
    l1 = jax.tree.leaves(s1.params)
    l2 = jax.tree.leaves(s2.params)
    for a, b in zip(l1, l2):
        # Adam's sqrt(v) denominator amplifies fp-reassociation noise from the
        # different chunk boundaries; allow a loose per-element tolerance.
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-3, atol=1e-4)


@pytest.mark.slow
def test_sharded_train_step_runs_and_matches():
    """FSDP+TP sharded step == single-device step."""
    ctx = MeshConfig(dp_shard=4, tp=2).build()
    params = decoder.init(CFG, jax.random.key(0))
    tx = OptimizerConfig(lr=1e-3, weight_decay=0.0).build()

    def loss_sharded(p, batch, rng):
        hidden = decoder.forward(p, CFG, batch["input_ids"], return_hidden=True, mesh_ctx=ctx)
        return fused_linear_cross_entropy(hidden, p["lm_head"]["kernel"], batch["labels"], chunk_size=32)

    shardings = logical_to_shardings(
        decoder.param_specs(CFG), ctx, shapes=jax.tree.map(lambda p: p.shape, params)
    )
    sp = jax.device_put(params, shardings)
    state_sharded = init_train_state(sp, tx)
    batch = _make_batch(jax.random.key(3), 1, 8, 16)
    batch_sharded = jax.device_put(batch, ctx.sharding(None, "batch", None))

    step_ref = jax.jit(make_train_step(_loss_fn, tx))
    step_shd = jax.jit(make_train_step(loss_sharded, tx))
    _, m_ref = step_ref(init_train_state(params, tx), batch, jax.random.key(0))
    _, m_shd = step_shd(state_sharded, batch_sharded, jax.random.key(0))
    np.testing.assert_allclose(float(m_ref["loss"]), float(m_shd["loss"]), rtol=1e-4)
    np.testing.assert_allclose(float(m_ref["grad_norm"]), float(m_shd["grad_norm"]), rtol=1e-3)


@pytest.mark.slow
def test_hsdp_sharded_train_step_matches():
    """HSDP (dp_replicate x dp_shard) == single-device step."""
    ctx = MeshConfig(dp_replicate=2, dp_shard=2, tp=2).build()
    params = decoder.init(CFG, jax.random.key(0))
    tx = OptimizerConfig(lr=1e-3, weight_decay=0.0).build()

    def loss_sharded(p, batch, rng):
        hidden = decoder.forward(p, CFG, batch["input_ids"], return_hidden=True, mesh_ctx=ctx)
        return fused_linear_cross_entropy(hidden, p["lm_head"]["kernel"], batch["labels"], chunk_size=32)

    shardings = logical_to_shardings(
        decoder.param_specs(CFG), ctx, shapes=jax.tree.map(lambda p: p.shape, params)
    )
    sp = jax.device_put(params, shardings)
    # params replicate over dp_replicate: each param lives on twice as many
    # devices as pure-FSDP sharding alone would imply
    q = sp["layers"]["q_proj"]["kernel"]
    assert len(q.sharding.device_set) == 8
    assert "dp_replicate" not in jax.tree.leaves([q.sharding.spec])[0:1][0]

    state_sharded = init_train_state(sp, tx)
    batch = _make_batch(jax.random.key(3), 1, 8, 16)
    batch_sharded = jax.device_put(batch, ctx.sharding(None, "batch", None))

    step_ref = jax.jit(make_train_step(_loss_fn, tx))
    step_shd = jax.jit(make_train_step(loss_sharded, tx))
    _, m_ref = step_ref(init_train_state(params, tx), batch, jax.random.key(0))
    _, m_shd = step_shd(state_sharded, batch_sharded, jax.random.key(0))
    np.testing.assert_allclose(float(m_ref["loss"]), float(m_shd["loss"]), rtol=1e-4)
    np.testing.assert_allclose(float(m_ref["grad_norm"]), float(m_shd["grad_norm"]), rtol=1e-3)
