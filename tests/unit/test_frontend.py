"""Online serving frontend: the live-traffic acceptance contract.

- STREAMS: tokens arrive per request in commit order, and the async
  loop's admission churn is invisible — greedy outputs are token-for-
  token identical to the offline `serve_batch` / `generate()` paths,
  with the step still compiling ONCE.
- BACKPRESSURE: a consumer that stops reading pauses only its own slot
  (bounded stream queue); everyone else keeps streaming.
- SHEDDING: deadline-aware admission control is pure step arithmetic —
  identical traces shed identical request sets.
- CANCELLATION: cancel storms mid-flight leak nothing — the allocator
  identity free + prefix-cached == total holds afterwards, including
  the disaggregated in-flight-handoff pin path.
- ADAPTIVE SPECULATION: per-request acceptance EWMA collapses the draft
  length to plain decode under zero acceptance, without touching parity.
- AUTOSCALER: the queue-imbalance policy fires with hysteresis and the
  router's borrow/return bookkeeping respects min_decode.
"""

import asyncio
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from automodel_tpu.inference.generate import GenerateConfig, generate
from automodel_tpu.models.llm import decoder
from automodel_tpu.models.llm.decoder import TransformerConfig
from automodel_tpu.serving import (
    AutoscaleConfig,
    DisaggConfig,
    DisaggOnlineFrontend,
    DisaggRouter,
    FrontendConfig,
    OnlineFrontend,
    PrefixCacheConfig,
    QueueAutoscaler,
    Request,
    ServingConfig,
    ServingEngine,
    SpeculativeConfig,
)
from automodel_tpu.serving.load_test import LoadTestConfig, run_load_test
from automodel_tpu.speculative.serve_draft import DraftSource

CFG = TransformerConfig(
    vocab_size=64, hidden_size=32, intermediate_size=48, num_layers=2,
    num_heads=4, num_kv_heads=2, qk_norm=True, dtype=jnp.float32,
    remat_policy="none",
)
FAST = FrontendConfig(idle_sleep_s=0.0002)


def _params():
    return decoder.init(CFG, jax.random.key(0))


def _engine(params, **geo):
    base = dict(page_size=4, num_pages=24, max_slots=3, pages_per_slot=6,
                token_budget=8, prefill_chunk=4)
    base.update(geo)
    return ServingEngine(params, CFG, ServingConfig(**base))


def _prompts(lens, vocab=64, seed0=0):
    return [
        [int(t) for t in np.random.default_rng(seed0 + i).integers(
            1, vocab, (l,))]
        for i, l in enumerate(lens)
    ]


def _ref(params, prompt, max_new):
    out = generate(
        params, CFG, jnp.asarray([prompt], jnp.int32), jax.random.key(0),
        GenerateConfig(max_new_tokens=max_new),
    )
    return [int(t) for t in np.asarray(out)[0, len(prompt):]]


# ---------------------------------------------------------------------------
# streaming: ordering + parity + compile-once
# ---------------------------------------------------------------------------

def test_streams_match_generate_and_compile_once():
    """Staggered live submissions through the async loop: every stream
    yields exactly the greedy `generate()` continuation, in order, and
    the engine step compiled once despite mid-flight admission."""
    params = _params()
    engine = _engine(params)
    prompts = _prompts([5, 9, 3, 7, 11])

    async def run():
        fe = OnlineFrontend(engine, FAST).start()
        streams = []
        for i, p in enumerate(prompts):
            if i >= 2:
                await fe.wait_step(i + 2)  # genuinely mid-flight
            streams.append(fe.submit(Request(prompt=list(p),
                                             max_new_tokens=6)))
        outs = await asyncio.gather(*(s.collect() for s in streams))
        stats = await fe.close()
        return outs, stats, streams

    outs, stats, streams = asyncio.run(run())
    for p, out in zip(prompts, outs):
        assert out == _ref(params, p, 6)
    assert all(s.finish_reason == "length" for s in streams)
    assert stats["compiled_signatures"] == 1
    assert stats["finished"] == 5 and stats["shed"] == 0


def test_load_test_harness_parity_under_sustained_load():
    """The load harness end to end on one replica: a paced many-request
    trace, all streams consumed concurrently, greedy parity re-checked
    offline, latency percentiles populated."""
    params = _params()
    engine = _engine(params, num_pages=96, max_slots=8, token_budget=16,
                     prefill_chunk=8)
    rep = run_load_test(
        engine,
        LoadTestConfig(num_requests=60, parity_check=20,
                       mean_interarrival_steps=0.3, seed=3),
        FAST,
    )
    assert rep["completed"] == 60 and rep["shed"] == 0
    assert rep["parity_checked"] == 20
    assert rep["ttft_p99_ms"] is not None and rep["itl_p99_ms"] is not None
    assert rep["frontend"]["compiled_signatures"] == 1


# ---------------------------------------------------------------------------
# backpressure
# ---------------------------------------------------------------------------

def test_slow_consumer_pauses_only_its_own_stream():
    """One consumer stops reading: its stream queue stays bounded by
    stream_buffer (the slot is withheld from plans), the OTHER requests
    run to completion meanwhile, and once the stalled consumer resumes
    it still receives its full, correct continuation."""
    params = _params()
    engine = _engine(params, num_pages=48, max_slots=3, pages_per_slot=12)
    cfg = dataclasses.replace(FAST, stream_buffer=4)
    prompts = _prompts([4, 6, 5])

    async def run():
        fe = OnlineFrontend(engine, cfg).start()
        slow = fe.submit(Request(prompt=list(prompts[0]),
                                 max_new_tokens=24))
        fast = [
            fe.submit(Request(prompt=list(p), max_new_tokens=24))
            for p in prompts[1:]
        ]
        # consume only the fast streams; the slow one is never read
        fast_outs = await asyncio.gather(*(s.collect() for s in fast))
        lag_while_stalled = slow._lag()
        paused = fe.sched.paused.copy()
        # resume the stalled consumer: it must still get everything
        slow_out = await slow.collect()
        await fe.close()
        return fast_outs, slow_out, lag_while_stalled, paused

    fast_outs, slow_out, lag, paused = asyncio.run(run())
    for p, out in zip(prompts[1:], fast_outs):
        assert out == _ref(params, p, 24)  # fast streams never stalled
    assert slow_out == _ref(params, prompts[0], 24)
    # bounded: buffer + at most one worst-case commit was ever queued
    assert lag <= 4
    assert paused, "the unread stream's slot should have been withheld"


# ---------------------------------------------------------------------------
# load shedding
# ---------------------------------------------------------------------------

def _shed_trace(params):
    """Overload a tiny engine with tight-deadline arrivals; return the
    per-rid finish reasons."""
    engine = _engine(params, num_pages=16, max_slots=2, pages_per_slot=8,
                     token_budget=4, prefill_chunk=4)
    prompts = _prompts([8, 8, 8, 8, 8, 8], seed0=11)

    async def run():
        fe = OnlineFrontend(engine, FAST).start()
        streams = [
            fe.submit(Request(prompt=list(p), max_new_tokens=4),
                      deadline_in=9)
            for p in prompts
        ]
        await asyncio.gather(*(s.collect() for s in streams))
        stats = await fe.close()
        return {s.rid: s.finish_reason for s in streams}, stats

    return asyncio.run(run())


def test_deadline_shedding_is_deterministic():
    """Six 8-token prompts with a 9-step deadline through a 4-token/step
    engine: the backlog makes the tail provably unreachable, so it sheds
    AT ADMISSION — and because the decision is pure step arithmetic, an
    identical trace sheds the identical rid set."""
    params = _params()
    reasons_a, stats_a = _shed_trace(params)
    reasons_b, stats_b = _shed_trace(params)
    assert reasons_a == reasons_b  # deterministic across runs
    shed = {r for r, why in reasons_a.items() if why == "shed"}
    done = {r for r, why in reasons_a.items() if why in ("eos", "length")}
    assert shed and done, f"want a mix under overload, got {reasons_a}"
    assert stats_a["shed"] == len(shed) == stats_b["shed"]
    # shed requests never occupied pool pages
    assert stats_a["free_pages"] == 16


def test_no_deadline_means_no_shedding():
    params = _params()
    engine = _engine(params, num_pages=16, max_slots=2, pages_per_slot=8,
                     token_budget=4, prefill_chunk=4)
    prompts = _prompts([8, 8, 8, 8], seed0=5)

    async def run():
        async with OnlineFrontend(engine, FAST) as fe:
            streams = [
                fe.submit(Request(prompt=list(p), max_new_tokens=3))
                for p in prompts
            ]
            outs = await asyncio.gather(*(s.collect() for s in streams))
        return outs

    outs = asyncio.run(run())
    for p, out in zip(prompts, outs):
        assert out == _ref(params, p, 3)


# ---------------------------------------------------------------------------
# cancellation
# ---------------------------------------------------------------------------

def test_cancel_storm_leaks_no_pages():
    """Cancel most of a live wave mid-generation (running AND queued):
    every cancelled stream terminates with reason "cancelled", survivors
    finish with parity, and afterwards every page is either free or held
    by the prefix cache: free + cached == total."""
    params = _params()
    engine = _engine(params, num_pages=40, max_slots=3, pages_per_slot=8,
                     prefix_cache=PrefixCacheConfig(enabled=True))
    prompts = _prompts([6, 7, 5, 9, 4, 8, 6, 7], seed0=23)

    async def run():
        fe = OnlineFrontend(engine, FAST).start()
        streams = [
            fe.submit(Request(prompt=list(p), max_new_tokens=20))
            for p in prompts
        ]
        await fe.wait_step(4)  # storm lands mid-generation
        for s in streams[2:]:
            fe.cancel(s.rid)
        keep = await asyncio.gather(*(s.collect() for s in streams[:2]))
        rest = await asyncio.gather(*(s.collect() for s in streams[2:]))
        stats = await fe.close()
        return keep, rest, stats, streams

    keep, rest, stats, streams = asyncio.run(run())
    for p, out in zip(prompts[:2], keep):
        assert out == _ref(params, p, 20)
    assert all(s.finish_reason == "cancelled" for s in streams[2:])
    assert stats["cancelled"] == 6
    assert engine.alloc.num_free + engine.prefix.cached_pages == 40
    assert engine.step_cache_size() == 1


def test_cancel_unknown_rid_is_noop():
    params = _params()
    engine = _engine(params)

    async def run():
        async with OnlineFrontend(engine, FAST) as fe:
            s = fe.submit(Request(prompt=[1, 2, 3], max_new_tokens=2))
            fe.cancel(999)  # never submitted: must not disturb anything
            return await s.collect()

    assert len(asyncio.run(run())) == 2


def test_disagg_cancel_releases_inflight_handoff_pins():
    """THE regression: cancelling a request whose KV handoff is IN FLIGHT
    (extracted from prefill, not yet admitted by decode) must drop the
    prefill-side page pins the same turn. Starve the decode class so
    handoffs pile up in flight, cancel them there, then drain — every
    replica's pool must return to free + cached == total."""
    params = _params()
    router = DisaggRouter(
        params, CFG,
        ServingConfig(page_size=4, num_pages=16, max_slots=2,
                      pages_per_slot=4, token_budget=8, prefill_chunk=8),
        DisaggConfig(enabled=True, prefill_replicas=1, decode_replicas=1),
    )

    async def run():
        fe = DisaggOnlineFrontend(router, FAST).start()
        streams = [
            fe.submit(Request(prompt=list(p), max_new_tokens=8))
            for p in _prompts([6, 6, 6, 6, 5, 7], seed0=31)
        ]
        # wait for the decode class to saturate and handoffs to queue
        for _ in range(4000):
            if fe.inflight:
                break
            await asyncio.sleep(0.001)
        assert fe.inflight, "decode starvation should strand handoffs"
        stranded = [h.req.rid for h in fe.inflight]
        for rid in stranded:
            fe.cancel(rid)
        for s in streams:
            if s.rid not in stranded:
                fe.cancel(s.rid)
        await asyncio.gather(*(s.collect() for s in streams))
        stats = await fe.close()
        return fe, stats, streams

    fe, stats, streams = asyncio.run(run())
    assert stats["cancelled_inflight"] >= 1
    assert stats["inflight_handoffs"] == 0
    assert all(s.finish_reason == "cancelled" for s in streams)
    for sched in fe.p_scheds + fe.d_scheds:
        cached = sched.prefix.cached_pages if sched.prefix is not None else 0
        assert sched.alloc.num_free + cached == 16, (
            "handoff pins leaked pages"
        )


# ---------------------------------------------------------------------------
# adaptive speculative draft length
# ---------------------------------------------------------------------------

class _AlwaysWrongDraft(DraftSource):
    """Drafts a token guaranteed != the greedy target at every position
    (t -> (t % (V-1)) + 1 never maps to itself for t in [0, V-1]), by
    cheating from the precomputed reference continuation."""

    def __init__(self, refs: dict):
        self.refs = refs  # rid -> full greedy continuation

    def draft(self, req, k: int) -> list:
        ref = self.refs[req.rid]
        g = len(req.generated)
        out = []
        for i in range(k):
            t = ref[g + i] if g + i < len(ref) else 1
            out.append((t % (CFG.vocab_size - 1)) + 1)
        return out


def test_adaptive_draft_len_collapses_to_plain_decode():
    """Zero acceptance: the per-request EWMA (decay 0.5, threshold 0.5)
    walks 1.0 -> 0.5 -> 0.25 -> 0.125, capping K at 4, 4, 1, 0 — so a
    hopeless drafter costs exactly 9 drafted tokens per request and then
    the slot IS a plain decode slot (and parity is untouched). The fixed
    -K engine keeps paying for the full run."""
    params = _params()
    prompts = _prompts([5, 7], seed0=41)
    max_new = 16
    refs = {i: _ref(params, p, max_new) for i, p in enumerate(prompts)}
    # budget 16: both decode slots always fit a full K=4 block, so the
    # collapse arithmetic below is exact (a tighter budget would clip
    # blocks and merely slow the decay)
    geo = dict(page_size=4, num_pages=32, max_slots=2, pages_per_slot=8,
               token_budget=16, prefill_chunk=4)

    def serve(adaptive):
        spec = SpeculativeConfig(
            enabled=True, draft_len=4, adaptive=adaptive,
            adaptive_threshold=0.5, adaptive_decay=0.5,
        )
        engine = ServingEngine(
            params, CFG, ServingConfig(**geo, speculative=spec),
            draft_source=_AlwaysWrongDraft(refs),
        )
        reqs = [
            Request(prompt=list(p), max_new_tokens=max_new, rid=i)
            for i, p in enumerate(prompts)
        ]
        return engine.serve_batch(reqs)

    adap = serve(adaptive=True)
    fixed = serve(adaptive=False)
    for res in (adap, fixed):
        assert res["stats"]["accepted_tokens"] == 0
        for i, p in enumerate(prompts):
            assert res["outputs"][i] == refs[i]  # parity regardless
    # collapse: 4 + 4 + 1 drafted tokens per request, then plain decode
    assert adap["stats"]["drafted_tokens"] == 9 * len(prompts)
    assert fixed["stats"]["drafted_tokens"] > adap["stats"]["drafted_tokens"]
    for req in adap["requests"]:
        assert req.spec_ewma == pytest.approx(0.125)


# ---------------------------------------------------------------------------
# autoscaler
# ---------------------------------------------------------------------------

def test_queue_autoscaler_hysteresis():
    cfg = AutoscaleConfig(enabled=True, grow_ratio=4.0, shrink_ratio=1.0,
                          sustain=3, cooldown=10, min_decode=1)
    pol = QueueAutoscaler(cfg)
    # two imbalanced turns: below sustain, no action
    assert pol.observe(40, 2, 0) is None
    assert pol.observe(40, 2, 1) is None
    # third consecutive -> grow
    assert pol.observe(40, 2, 2) == "grow"
    # still imbalanced but inside cooldown -> quiet
    for t in range(3, 12):
        assert pol.observe(40, 2, t) is None
    # cooldown over and the streak held -> grow again
    assert pol.observe(40, 2, 12) == "grow"
    # a single balanced turn resets the shrink streak too
    assert pol.observe(1, 5, 23) is None
    assert pol.observe(30, 2, 24) is None  # grow streak restarted at 1
    # sustained balance -> shrink (after its own sustain + cooldown)
    for t in range(25, 27):
        assert pol.observe(0, 5, t) is None
    assert pol.observe(0, 5, 27) == "shrink"


class _FakeAlloc:
    def __init__(self, free):
        self.num_free = free


class _FakeSched:
    def __init__(self, waiting=0, running=0, free=10):
        self.waiting = [None] * waiting
        self.running = {i: None for i in range(running)}
        self.alloc = _FakeAlloc(free)


def test_disagg_router_borrow_and_return_bookkeeping():
    """autoscale_tick on a shell router: sustained prefill overload
    borrows the freest decode replica (never below min_decode dedicated),
    sustained balance returns the most recent borrow."""
    router = object.__new__(DisaggRouter)
    router.disagg = DisaggConfig(
        enabled=True, prefill_replicas=1, decode_replicas=3,
        autoscale=AutoscaleConfig(enabled=True, sustain=2, cooldown=0,
                                  min_decode=2),
    )
    router.autoscaler = QueueAutoscaler(router.disagg.autoscale)
    router.borrowed = set()
    router.decode = [None] * 3
    router.n_borrows = router.n_returns = 0

    p = [_FakeSched(waiting=30)]
    d = [_FakeSched(free=4), _FakeSched(free=9), _FakeSched(free=6)]
    assert router.autoscale_tick(p, d, 0) is None
    assert router.autoscale_tick(p, d, 1) == "grow"
    assert router.borrowed == {1}  # the freest decode replica
    # next grow would dip below min_decode=2 dedicated -> refused
    assert router.autoscale_tick(p, d, 2) is None
    assert router.autoscale_tick(p, d, 3) is None
    assert router.borrowed == {1} and router.n_borrows == 1
    # balance restored -> the borrow comes back
    q = [_FakeSched(waiting=0)]
    assert router.autoscale_tick(q, d, 4) is None
    assert router.autoscale_tick(q, d, 5) == "shrink"
    assert router.borrowed == set() and router.n_returns == 1


def test_disagg_autoscale_borrowed_replica_serves_prefill():
    """End to end with engines: force a borrow, then verify arrivals
    routed to the borrowed decode replica prefill there, hand off with
    the rids guard (its own decode work untouched), and parity holds."""
    params = _params()
    router = DisaggRouter(
        params, CFG,
        ServingConfig(page_size=4, num_pages=32, max_slots=2,
                      pages_per_slot=6, token_budget=8, prefill_chunk=4),
        DisaggConfig(
            enabled=True, prefill_replicas=1, decode_replicas=2,
            autoscale=AutoscaleConfig(enabled=True, grow_ratio=2.0,
                                      sustain=1, cooldown=0, min_decode=1),
        ),
    )
    prompts = _prompts([5, 6, 4, 7, 5, 6, 4, 5], seed0=53)

    async def run():
        fe = DisaggOnlineFrontend(router, FAST).start()
        # first wave overloads the single prefill replica -> borrow fires
        streams = [
            fe.submit(Request(prompt=list(p), max_new_tokens=4))
            for p in prompts[:6]
        ]
        await fe.wait_step(3)
        # second wave arrives while borrowed: routes to the (empty)
        # borrowed decode replica, prefills there, hands off under the
        # rids guard
        streams += [
            fe.submit(Request(prompt=list(p), max_new_tokens=4))
            for p in prompts[6:]
        ]
        outs = await asyncio.gather(*(s.collect() for s in streams))
        stats = await fe.close()
        return outs, stats

    outs, stats = asyncio.run(run())
    for p, out in zip(prompts, outs):
        assert out == _ref(params, p, 4)
    assert stats["autoscale_borrows"] >= 1
    # compile-once per replica class survives the routing-set change
    assert stats["compiled_signatures_prefill"] == 1
    assert stats["compiled_signatures_decode"] == 1
