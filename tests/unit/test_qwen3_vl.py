"""Qwen3-VL-MoE: deepstack ViT + interleaved-MRoPE qwen3-moe text."""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from automodel_tpu.models.registry import get_model_spec
from automodel_tpu.models.vlm import qwen3_vl

Q3VL_HF = {
    "architectures": ["Qwen3VLMoeForConditionalGeneration"],
    "model_type": "qwen3_vl_moe",
    "image_token_id": 120,
    "vision_config": {
        "patch_size": 14, "temporal_patch_size": 2, "spatial_merge_size": 2,
        "num_heads": 2, "depth": 3, "hidden_size": 32, "intermediate_size": 48,
        "out_hidden_size": 32, "num_position_embeddings": 64,
        "deepstack_visual_indexes": [0, 1],
    },
    "text_config": {
        "vocab_size": 128, "hidden_size": 32, "intermediate_size": 64,
        "num_hidden_layers": 2, "num_attention_heads": 4,
        "num_key_value_heads": 2, "head_dim": 8,
        "num_experts": 4, "num_experts_per_tok": 2,
        "moe_intermediate_size": 16, "norm_topk_prob": True,
        "rope_scaling": {"mrope_section": [2, 1, 1], "mrope_interleaved": True},
    },
}


def _setup():
    spec = get_model_spec(Q3VL_HF)
    cfg = spec.config_from_hf(Q3VL_HF, dtype=jnp.float32, remat_policy="none")
    params = qwen3_vl.init(cfg, jax.random.key(0))
    return spec, cfg, params


def _mock_batch(cfg, B=2, S=32, img=56):
    n_img = (img // cfg.vision.patch_size // cfg.vision.spatial_merge_size) ** 2
    rng = np.random.default_rng(0)
    text = rng.integers(1, 100, (B, S - n_img), dtype=np.int32)
    ids = np.concatenate(
        [text[:, :4], np.full((B, n_img), cfg.image_token_id, np.int32), text[:, 4:]],
        axis=1,
    )
    pixels = rng.normal(size=(B, img, img, 3)).astype(np.float32)
    return jnp.asarray(ids), jnp.asarray(pixels)


@pytest.mark.slow
def test_qwen3_vl_forward_and_deepstack():
    spec, cfg, params = _setup()
    ids, pixels = _mock_batch(cfg)
    hidden, aux, stats = qwen3_vl.forward(
        params, cfg, ids, pixels, return_hidden=True, return_stats=True
    )
    assert hidden.shape == (2, 32, 32)
    assert np.isfinite(np.asarray(hidden)).all()
    assert stats["tokens_per_expert"].shape == (2, 4)

    # deepstack is live: zeroing the deepstack mergers changes the output
    z = jax.tree.map(lambda x: x, params)
    z["visual"]["deepstack_mergers"] = jax.tree.map(
        jnp.zeros_like, z["visual"]["deepstack_mergers"]
    )
    h2, _, _ = qwen3_vl.forward(z, cfg, ids, pixels, return_hidden=True, return_stats=True)
    assert np.abs(np.asarray(hidden) - np.asarray(h2)).max() > 1e-5


@pytest.mark.slow
def test_qwen3_vl_text_only_matches_plain_decoder():
    """With no image tokens, MRoPE collapses to standard rope (t=h=w=index)
    and deepstack injects zeros — forward must equal the plain MoE decoder."""
    from automodel_tpu.models.moe_lm import decoder as moe_decoder

    spec, cfg, params = _setup()
    rng = np.random.default_rng(1)
    ids = jnp.asarray(rng.integers(1, 100, (2, 16), dtype=np.int32))
    pixels = jnp.asarray(rng.normal(size=(2, 56, 56, 3)).astype(np.float32))
    h_vl, _, _ = qwen3_vl.forward(
        params, cfg, ids, pixels, return_hidden=True, return_stats=True
    )
    h_txt, _ = moe_decoder.forward(
        params["language_model"], cfg.text, ids, return_hidden=True
    )
    np.testing.assert_allclose(np.asarray(h_vl), np.asarray(h_txt), atol=1e-5)


def test_mrope_positions_match_hf_semantics():
    """Pinned to transformers qwen2_5_vl get_rope_index: image block gets
    (0, row, col) + image start; following text resumes at max+1."""
    ids = jnp.asarray([[5, 9, 9, 9, 9, 7, 8]])  # 2x2 merged image at 1..4
    mask = ids == 9
    pos3 = np.asarray(qwen3_vl.get_mrope_positions(ids, mask, 2, 2))
    # text token 0 → 0; image start=1: t=1, h=1+row, w=1+col
    np.testing.assert_array_equal(pos3[:, 0, 0], [0, 0, 0])
    np.testing.assert_array_equal(pos3[0, 0, 1:5], [1, 1, 1, 1])       # t
    np.testing.assert_array_equal(pos3[1, 0, 1:5], [1, 1, 2, 2])       # h
    np.testing.assert_array_equal(pos3[2, 0, 1:5], [1, 2, 1, 2])       # w
    # text resumes at img_start + max(gh,gw) = 3 → positions 3, 4
    np.testing.assert_array_equal(pos3[:, 0, 5], [3, 3, 3])
    np.testing.assert_array_equal(pos3[:, 0, 6], [4, 4, 4])


def test_mrope_axis_maps():
    m = qwen3_vl.mrope_axis_map((2, 1, 1), interleaved=False, n_freq=4)
    np.testing.assert_array_equal(np.asarray(m), [0, 0, 1, 2])
    m = qwen3_vl.mrope_axis_map((2, 1, 1), interleaved=True, n_freq=4)
    np.testing.assert_array_equal(np.asarray(m), [0, 1, 2, 0])


@pytest.mark.slow
def test_qwen3_vl_generate_matches_naive():
    """vlm_generate greedy == teacher-forced qwen3_vl.forward argmax loop —
    proves the KV-cache decode path carries MRoPE geometry (rope position ≠
    cache slot after an image block) and deepstack residuals correctly."""
    from automodel_tpu.inference.generate import GenerateConfig, vlm_generate

    spec, cfg, params = _setup()
    ids, pixels = _mock_batch(cfg, B=2, S=16, img=56)
    out = vlm_generate(
        qwen3_vl, params, cfg, ids, pixels,
        jax.random.key(1), GenerateConfig(max_new_tokens=4),
    )
    cur = ids
    for _ in range(4):
        logits, _aux = qwen3_vl.forward(params, cfg, cur, pixels)
        nxt = jnp.argmax(logits[:, -1], -1).astype(jnp.int32)
        cur = jnp.concatenate([cur, nxt[:, None]], 1)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(cur))


@pytest.mark.slow
def test_qwen3_vl_decode_rope_origin():
    """prepare_generation: the first decoded token's rope position resumes
    at max(pos3)+1 — NOT at the prompt length (the image block compresses
    positions by its token count minus max(gh,gw))."""
    spec, cfg, params = _setup()
    ids, pixels = _mock_batch(cfg, B=1, S=16, img=56)
    prep = qwen3_vl.prepare_generation(params, cfg, ids, pixels)
    image_mask = np.asarray(ids) == cfg.image_token_id
    pos3 = np.asarray(qwen3_vl.get_mrope_positions(ids, jnp.asarray(image_mask), 2, 2))
    np.testing.assert_array_equal(np.asarray(prep["decode_rope_pos0"]), pos3.max((0, 2)) + 1)
    n_img = int(image_mask.sum())
    # image block advances positions by max(gh,gw)=2, not by its n_img tokens
    assert prep["decode_rope_pos0"][0] == ids.shape[1] - n_img + 2
    assert prep["decode_rope_pos0"][0] < ids.shape[1]  # compressed vs slots
    assert prep["rope_angles"].shape[:2] == ids.shape
    assert prep["deepstack_embeds"].shape[0] == len(cfg.vision.deepstack_visual_indexes)


@pytest.mark.slow
def test_qwen3_vl_adapter_roundtrip():
    from automodel_tpu.checkpoint.hf_adapter import get_adapter

    spec, cfg, params = _setup()
    ad = get_adapter(spec.adapter_name, cfg, **spec.adapter_kwargs)
    sd = dict(ad.to_hf(params))
    assert sd["model.visual.patch_embed.proj.weight"].shape == (32, 3, 2, 14, 14)
    assert sd["model.visual.pos_embed.weight"].shape == (64, 32)
    assert "model.visual.deepstack_merger_list.1.linear_fc2.weight" in sd
    assert sd["model.language_model.layers.0.mlp.experts.gate_up_proj"].shape == (4, 32, 32)
    assert sd["model.language_model.layers.0.mlp.experts.down_proj"].shape == (4, 16, 32)
    p2 = ad.from_hf(lambda k: np.asarray(sd[k]))
    ids, pixels = _mock_batch(cfg)
    o1, _, _ = qwen3_vl.forward(params, cfg, ids, pixels, return_stats=True)
    o2, _, _ = qwen3_vl.forward(
        jax.tree.map(jnp.asarray, p2), cfg, ids, pixels, return_stats=True
    )
    np.testing.assert_allclose(np.asarray(o1), np.asarray(o2), atol=1e-5)


@pytest.mark.recipe
@pytest.mark.slow  # qwen3_vl_moe example smoke + model pin cover the family
def test_qwen3_vl_recipe_trains(tmp_path):
    from automodel_tpu.cli.app import resolve_recipe_class
    from automodel_tpu.config import ConfigNode

    cfg = ConfigNode({
        "seed": 7,
        "run_dir": str(tmp_path),
        "auto_resume": False,
        "recipe": "vlm_finetune",
        "model": {"hf_config": Q3VL_HF, "dtype": "float32", "remat_policy": "none"},
        "distributed": {"dp_shard": -1, "ep": 2},
        "dataset": {
            "_target_": "automodel_tpu.datasets.vlm.MockVLMDatasetConfig",
            "num_samples": 32, "seq_len": 32, "vocab_size": 128,
            "image_size": 56, "patch_size": 14, "merge_factor": 2,
            "image_token_id": 120,
        },
        "dataloader": {"microbatch_size": 8, "grad_acc_steps": 1},
        "optimizer": {"name": "adamw", "lr": 1e-3},
        "lr_scheduler": {"style": "constant", "warmup_steps": 0},
        "step_scheduler": {"max_steps": 3, "ckpt_every_steps": 100},
        "checkpoint": {"enabled": False},
        "loss": {"chunk_size": 64},
    })
    r = resolve_recipe_class(cfg)(cfg)
    r.setup()
    assert r.is_moe
    r.run_train_validation_loop()
    recs = [json.loads(l) for l in open(tmp_path / "training.jsonl") if l.strip()]
    assert len(recs) == 3
    assert all(np.isfinite(x["loss"]) for x in recs)
