import jax
import jax.numpy as jnp
import numpy as np
import pytest

from automodel_tpu.distributed import MeshConfig
from automodel_tpu.models.moe_lm import decoder as moe_decoder
from automodel_tpu.models.moe_lm.decoder import MoETransformerConfig
from automodel_tpu.moe.config import MoEConfig
from automodel_tpu.moe.experts import compute_capacity, dispatch_tensors
from automodel_tpu.moe.gate import gate_forward, init_gate, update_gate_bias
from automodel_tpu.moe.layer import init_moe, moe_forward
from automodel_tpu.parallel import logical_to_shardings

MOE = MoEConfig(
    n_routed_experts=4,
    experts_per_token=2,
    moe_intermediate_size=32,
    aux_loss_coeff=0.01,
    capacity_factor=2.0,
)


def test_gate_topk_and_weights():
    params = init_gate(MOE, 16, jax.random.key(0))
    x = jax.random.normal(jax.random.key(1), (10, 16))
    w, idx, aux, stats = gate_forward(params, MOE, x)
    assert w.shape == (10, 2) and idx.shape == (10, 2)
    np.testing.assert_allclose(np.asarray(w.sum(-1)), 1.0, rtol=1e-5)  # norm_topk
    assert float(aux) > 0
    assert int(stats["tokens_per_expert"].sum()) == 20


def test_fake_balanced_gate_uniform():
    cfg = MoEConfig(n_routed_experts=4, experts_per_token=2, fake_balanced_gate=True)
    w, idx, aux, _ = gate_forward({}, cfg, jnp.zeros((8, 16)))
    counts = np.bincount(np.asarray(idx).ravel(), minlength=4)
    assert (counts == 4).all()
    assert float(aux) == 0.0


def test_group_limited_routing():
    cfg = MoEConfig(n_routed_experts=8, experts_per_token=2, n_groups=4, topk_groups=1)
    params = init_gate(cfg, 16, jax.random.key(0))
    x = jax.random.normal(jax.random.key(2), (6, 16))
    _, idx, _, _ = gate_forward(params, cfg, x)
    # both selected experts must come from the same (single) chosen group
    groups = np.asarray(idx) // 2
    assert (groups[:, 0] == groups[:, 1]).all()


def test_gate_bias_update_direction():
    cfg = MoEConfig(n_routed_experts=4, gate_bias_update_speed=0.1)
    params = init_gate(cfg, 16, jax.random.key(0))
    tokens = jnp.asarray([10.0, 0.0, 5.0, 5.0])
    new = update_gate_bias(params, cfg, tokens)
    b = np.asarray(new["e_score_bias"])
    assert b[0] < 0 and b[1] > 0 and b[2] == 0 and b[3] == 0


def test_dispatch_combine_roundtrip():
    """With ample capacity every token reaches its experts exactly once."""
    idx = jnp.asarray([[0, 1], [1, 2], [3, 0]], jnp.int32)
    w = jnp.full((3, 2), 0.5)
    cap = compute_capacity(MOE, 3)
    disp, comb_w = dispatch_tensors(MOE, idx, w, cap)
    assert float(disp.sum()) == 6.0  # all (token, slot) pairs kept
    np.testing.assert_allclose(np.asarray(comb_w.sum(1)), 1.0)


def test_capacity_drop():
    cfg = MoEConfig(n_routed_experts=2, experts_per_token=1, capacity_factor=1.0)
    # all 8 tokens to expert 0; capacity = 8*1/2 = 4 → sublane-aligned 8? use 16 tokens
    idx = jnp.zeros((16, 1), jnp.int32)
    w = jnp.ones((16, 1))
    disp, _ = dispatch_tensors(cfg, idx, w, 8)
    assert float(disp.sum()) == 8.0  # half dropped


def test_moe_forward_matches_dense_reference():
    """Capacity-dispatch output == naive per-token loop (ample capacity)."""
    params = init_moe(MOE, 16, jax.random.key(0))
    x = jax.random.normal(jax.random.key(3), (2, 5, 16))
    out, aux, _ = moe_forward(params, MOE, x)
    assert out.shape == x.shape

    flat = x.reshape(10, 16)
    w, idx, _, _ = gate_forward(params["gate"], MOE, flat)
    expected = np.zeros((10, 16), np.float32)
    ek = params["experts"]
    for t in range(10):
        for j in range(2):
            e = int(idx[t, j])
            g = jax.nn.silu(flat[t] @ ek["gate_proj"]["kernel"][e])
            u = flat[t] @ ek["up_proj"]["kernel"][e]
            expected[t] += float(w[t, j]) * np.asarray((g * u) @ ek["down_proj"]["kernel"][e])
    np.testing.assert_allclose(np.asarray(out.reshape(10, 16)), expected, rtol=2e-3, atol=2e-3)


MOE_LM = MoETransformerConfig(
    vocab_size=64,
    hidden_size=32,
    intermediate_size=48,
    num_layers=3,
    num_heads=4,
    num_kv_heads=2,
    first_k_dense=1,
    moe=MoEConfig(
        n_routed_experts=4,
        n_shared_experts=1,
        experts_per_token=2,
        moe_intermediate_size=16,
        shared_expert_intermediate_size=16,
        aux_loss_coeff=0.01,
        capacity_factor=2.0,
    ),
    dtype=jnp.float32,
    remat_policy="none",
)


def test_moe_decoder_forward():
    params = moe_decoder.init(MOE_LM, jax.random.key(0))
    logits, aux = moe_decoder.forward(params, MOE_LM, jnp.zeros((2, 8), jnp.int32))
    assert logits.shape == (2, 8, 64)
    assert float(aux) > 0
    assert np.isfinite(np.asarray(logits)).all()


def test_moe_decoder_specs_match():
    params = moe_decoder.init(MOE_LM, jax.random.key(0))
    specs = moe_decoder.param_specs(MOE_LM)
    flat_p = jax.tree.leaves(params)
    flat_s = jax.tree.leaves(specs, is_leaf=lambda x: isinstance(x, tuple))
    assert len(flat_p) == len(flat_s)
    for p, s in zip(flat_p, flat_s):
        assert p.ndim == len(s), f"{p.shape} vs {s}"


@pytest.mark.slow
def test_moe_sharded_ep_matches_single_device():
    ctx = MeshConfig(dp_shard=2, ep=4).build()
    params = moe_decoder.init(MOE_LM, jax.random.key(0))
    ids = jax.random.randint(jax.random.key(5), (8, 8), 0, 64)
    ref, ref_aux = moe_decoder.forward(params, MOE_LM, ids)

    shardings = logical_to_shardings(
        moe_decoder.param_specs(MOE_LM), ctx,
        shapes=jax.tree.map(lambda p: p.shape, params),
    )
    sp = jax.device_put(params, shardings)

    @jax.jit
    def f(p, i):
        return moe_decoder.forward(p, MOE_LM, i, mesh_ctx=ctx)

    out, aux = f(sp, jax.device_put(ids, ctx.sharding("batch", None)))
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out), rtol=2e-3, atol=2e-3)
    np.testing.assert_allclose(float(ref_aux), float(aux), rtol=1e-4)


def test_moe_registry():
    from automodel_tpu.models.registry import get_model_spec

    hf = {
        "architectures": ["Qwen3MoeForCausalLM"],
        "vocab_size": 64, "hidden_size": 32, "intermediate_size": 48,
        "num_hidden_layers": 2, "num_attention_heads": 4, "num_key_value_heads": 2,
        "num_experts": 4, "num_experts_per_tok": 2, "moe_intermediate_size": 16,
        "norm_topk_prob": True,
    }
    spec = get_model_spec(hf)
    cfg = spec.config_from_hf(hf, dtype=jnp.float32, remat_policy="none")
    assert cfg.qk_norm and cfg.moe.n_routed_experts == 4
    params = spec.module.init(cfg, jax.random.key(0))
    logits, aux = spec.module.forward(params, cfg, jnp.zeros((1, 4), jnp.int32))
    assert logits.shape == (1, 4, 64)


def test_gate_token_mask_excludes_padding():
    params = init_gate(MOE, 16, jax.random.key(0))
    x = jax.random.normal(jax.random.key(7), (10, 16))
    mask = jnp.asarray([True] * 6 + [False] * 4)
    w, idx, aux, stats = gate_forward(params, MOE, x, mask)
    # masked tokens route to the invalid expert index E and carry zero weight
    assert (np.asarray(idx[6:]) == MOE.n_routed_experts).all()
    assert float(np.abs(np.asarray(w[6:])).sum()) == 0.0
    assert int(stats["tokens_per_expert"].sum()) == 12  # 6 tokens * k=2
    # masked tokens consume no capacity
    disp, _ = dispatch_tensors(MOE, idx, w, 8)
    assert float(disp.sum()) == 12.0


def test_moe_stats_and_bias_update():
    from automodel_tpu.models.moe_lm.decoder import apply_gate_bias_update
    import dataclasses

    cfg = dataclasses.replace(
        MOE_LM,
        moe=dataclasses.replace(MOE_LM.moe, gate_bias_update_speed=0.05),
    )
    params = moe_decoder.init(cfg, jax.random.key(0))
    assert "e_score_bias" in params["moe_layers"]["moe"]["gate"]
    ids = jax.random.randint(jax.random.key(1), (2, 8), 0, 64)
    out, aux, stats = moe_decoder.forward(params, cfg, ids, return_stats=True)
    tpe = stats["tokens_per_expert"]
    assert tpe.shape == (cfg.num_moe_layers, 4)
    assert float(tpe.sum()) == cfg.num_moe_layers * 16 * 2  # all tokens routed
    new = apply_gate_bias_update(params, cfg, tpe)
    assert not np.allclose(
        np.asarray(new["moe_layers"]["moe"]["gate"]["e_score_bias"]), 0.0
    )


def test_moe_layer_types_windows():
    import dataclasses

    cfg = dataclasses.replace(
        MOE_LM,
        first_k_dense=0,
        num_layers=2,
        sliding_window=2,
        layer_types=("sliding", "global"),
    )
    cfg_all = dataclasses.replace(cfg, layer_types=None)
    params = moe_decoder.init(cfg, jax.random.key(0))
    ids = jnp.arange(12, dtype=jnp.int32)[None, :] % 64
    out_mix, _ = moe_decoder.forward(params, cfg, ids)
    out_all, _ = moe_decoder.forward(params, cfg_all, ids)
    assert not np.allclose(np.asarray(out_mix), np.asarray(out_all))


@pytest.mark.slow
def test_deepseek_v3_mla_end_to_end(tmp_path):
    """DSv3-style config: MLA + sigmoid grouped gate + shared experts +
    first-k dense; forward, grads, EP sharding, HF checkpoint roundtrip."""
    import dataclasses as dc

    from automodel_tpu.checkpoint import (
        HFCheckpointReader,
        MoEDecoderAdapter,
        save_hf_checkpoint,
    )
    from automodel_tpu.models.registry import get_model_spec

    hf = {
        "architectures": ["DeepseekV3ForCausalLM"],
        "vocab_size": 64, "hidden_size": 32, "intermediate_size": 48,
        "num_hidden_layers": 3, "num_attention_heads": 4,
        "num_key_value_heads": 4,
        "q_lora_rank": 12, "kv_lora_rank": 16,
        "qk_nope_head_dim": 8, "qk_rope_head_dim": 4, "v_head_dim": 8,
        "n_routed_experts": 8, "n_shared_experts": 1, "num_experts_per_tok": 2,
        "n_group": 4, "topk_group": 2, "moe_intermediate_size": 16,
        "first_k_dense_replace": 1, "routed_scaling_factor": 2.5,
        "scoring_func": "sigmoid", "norm_topk_prob": True,
    }
    spec = get_model_spec(hf)
    cfg = spec.config_from_hf(hf, dtype=jnp.float32, remat_policy="none")
    assert cfg.attention_type == "mla" and cfg.moe.score_func == "sigmoid"
    assert cfg.moe.gate_bias_update_speed > 0  # aux-free balancing default

    params = spec.module.init(cfg, jax.random.key(0))
    ids = jax.random.randint(jax.random.key(1), (2, 8), 0, 64)
    logits, aux = spec.module.forward(params, cfg, ids)
    assert logits.shape == (2, 8, 64)
    assert np.isfinite(np.asarray(logits)).all()

    # causality holds through MLA
    ids2 = ids.at[0, 6].set((int(ids[0, 6]) + 1) % 64)
    l2, _ = spec.module.forward(params, cfg, ids2)
    np.testing.assert_allclose(
        np.asarray(logits[0, :6]), np.asarray(l2[0, :6]), rtol=2e-5, atol=2e-5
    )

    # sharded parity incl. ep
    ctx = MeshConfig(dp_shard=2, ep=4).build()
    from automodel_tpu.parallel import logical_to_shardings

    sh = logical_to_shardings(
        spec.module.param_specs(cfg), ctx,
        shapes=jax.tree.map(lambda p: p.shape, params),
    )
    sp = jax.device_put(params, sh)

    @jax.jit
    def f(p, i):
        return spec.module.forward(p, cfg, i, mesh_ctx=ctx)

    ids8 = jax.random.randint(jax.random.key(2), (8, 8), 0, 64)
    ref, _ = spec.module.forward(params, cfg, ids8)
    out, _ = f(sp, jax.device_put(ids8, ctx.sharding("batch", None)))
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out), rtol=2e-3, atol=2e-3)

    # HF checkpoint roundtrip with deepseek naming
    adapter = MoEDecoderAdapter(cfg, style="deepseek")
    save_hf_checkpoint(adapter.to_hf(params), str(tmp_path))
    reader = HFCheckpointReader(str(tmp_path))
    assert "model.layers.1.self_attn.kv_a_proj_with_mqa.weight" in reader.keys()
    assert "model.layers.1.self_attn.q_b_proj.weight" in reader.keys()
    restored = adapter.from_hf(reader)
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(restored)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))


def test_yarn_rope_and_rope_permutation():
    """Yarn frequencies behave (interp at low freq, original at high freq);
    the adapter's rope permutation is a true inverse pair and de-interleaves."""
    import numpy as np
    from automodel_tpu.checkpoint.hf_adapter import _permute_k_rope, _permute_q_rope
    from automodel_tpu.ops.rope import RopeScalingConfig, rope_frequencies

    base = rope_frequencies(64, 10000.0)
    yarn = rope_frequencies(
        64, 10000.0,
        RopeScalingConfig(rope_type="yarn", factor=4.0,
                          original_max_position_embeddings=2048,
                          beta_fast=32, beta_slow=1, mscale_all_dim=1.0),
    )
    # highest-frequency dims unchanged, lowest-frequency dims divided by 4
    np.testing.assert_allclose(np.asarray(yarn[0]), np.asarray(base[0]), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(yarn[-1]), np.asarray(base[-1]) / 4.0, rtol=1e-6)
    rs = RopeScalingConfig(rope_type="yarn", factor=4.0, mscale_all_dim=1.0)
    assert rs.yarn_mscale() > 1.0

    # permutation: interleaved (p0,p1,p2,...) → half-split (evens, odds)
    dn, dr, n = 2, 4, 2
    kernel = np.arange(3 * n * (dn + dr)).reshape(3, n * (dn + dr)).astype(np.float64)
    fwd = _permute_q_rope(kernel, n, dn, dr, inverse=False)
    # head 0 rope cols were [2,3,4,5] (interleaved pairs) → [2,4,3,5]
    np.testing.assert_array_equal(fwd[0, :6], [0, 1, 2, 4, 3, 5])
    back = _permute_q_rope(fwd, n, dn, dr, inverse=True)
    np.testing.assert_array_equal(back, kernel)
    kv = np.arange(2 * 7).reshape(2, 7).astype(np.float64)  # kv_rank=3, dr=4
    fwd = _permute_k_rope(kv, 3, 4, inverse=False)
    np.testing.assert_array_equal(fwd[0], [0, 1, 2, 3, 5, 4, 6])
    np.testing.assert_array_equal(_permute_k_rope(fwd, 3, 4, inverse=True), kv)


@pytest.mark.slow
def test_dropless_matches_capacity_with_ample_headroom():
    """With no drops possible, dropless == capacity dispatch exactly."""
    import dataclasses as dc

    from automodel_tpu.moe.experts import experts_forward_dropless
    from automodel_tpu.moe.layer import moe_forward as _mf

    cfg_cap = dc.replace(MOE, dispatcher="capacity", capacity_factor=4.0)
    cfg_drop = dc.replace(MOE, dispatcher="dropless")
    params = init_moe(cfg_cap, 16, jax.random.key(0))
    x = jax.random.normal(jax.random.key(4), (2, 6, 16))
    out_cap, aux1, _ = _mf(params, cfg_cap, x)
    out_drop, aux2, _ = _mf(params, cfg_drop, x)
    np.testing.assert_allclose(
        np.asarray(out_cap), np.asarray(out_drop), rtol=2e-3, atol=2e-3
    )
    np.testing.assert_allclose(float(aux1), float(aux2), rtol=1e-5)


def test_dropless_no_drops_under_imbalance():
    """All tokens route to ONE expert: capacity drops most, dropless keeps all."""
    import dataclasses as dc

    cfg = dc.replace(
        MOE, n_routed_experts=4, experts_per_token=1, capacity_factor=1.0,
        dispatcher="dropless",
    )
    params = init_moe(cfg, 16, jax.random.key(0))
    x = jax.random.normal(jax.random.key(5), (32, 16))
    w = jnp.ones((32, 1))
    idx = jnp.zeros((32, 1), jnp.int32)  # everyone → expert 0
    from automodel_tpu.moe.experts import experts_forward_dropless

    out = experts_forward_dropless(params["experts"], cfg, x, w, idx)
    # every row equals the dense expert-0 computation (nothing dropped)
    ek = params["experts"]
    g = jax.nn.silu(x @ ek["gate_proj"]["kernel"][0])
    u = x @ ek["up_proj"]["kernel"][0]
    ref = (g * u) @ ek["down_proj"]["kernel"][0]
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-3, atol=2e-3)


@pytest.mark.slow
def test_dropless_grads_and_masked_tokens():
    import dataclasses as dc

    cfg = dc.replace(MOE, dispatcher="dropless")
    params = init_moe(cfg, 16, jax.random.key(0))
    x = jax.random.normal(jax.random.key(6), (1, 8, 16))
    mask = jnp.asarray([[True] * 5 + [False] * 3])

    def loss(p):
        out, aux, _ = moe_forward(p, cfg, x, token_mask=mask)
        return jnp.sum(out ** 2) + aux

    g = jax.grad(loss)(params)
    for leaf in jax.tree.leaves(g):
        assert np.isfinite(np.asarray(leaf)).all()


@pytest.mark.slow
def test_gpt_oss_end_to_end(tmp_path):
    """gpt-oss: attention sinks + alternating windows + biased router +
    fused-gate_up swigluoai experts; forward, sinks effect, HF roundtrip."""
    from automodel_tpu.checkpoint import (
        HFCheckpointReader,
        MoEDecoderAdapter,
        save_hf_checkpoint,
    )
    from automodel_tpu.models.registry import get_model_spec

    hf = {
        "architectures": ["GptOssForCausalLM"],
        "vocab_size": 128, "hidden_size": 32, "intermediate_size": 32,
        "num_hidden_layers": 2, "num_attention_heads": 4,
        "num_key_value_heads": 2, "head_dim": 8,
        "num_local_experts": 4, "num_experts_per_tok": 2,
        "sliding_window": 4,
        "layer_types": ["sliding_attention", "full_attention"],
    }
    spec = get_model_spec(hf)
    cfg = spec.config_from_hf(hf, dtype=jnp.float32, remat_policy="none")
    assert cfg.attention_sinks and cfg.moe.router_bias and cfg.moe.expert_bias
    assert cfg.o_proj_bias
    assert cfg.moe.expert_activation == "swigluoai"
    assert cfg.layer_types == ("sliding", "global")

    params = spec.module.init(cfg, jax.random.key(0))
    assert "sinks" in params["moe_layers"]
    assert "bias" in params["moe_layers"]["moe"]["experts"]["gate_proj"]
    ids = jax.random.randint(jax.random.key(1), (2, 12), 0, 128)
    logits, aux = spec.module.forward(params, cfg, ids)
    assert np.isfinite(np.asarray(logits)).all()

    # sinks affect outputs
    p2 = jax.tree_util.tree_map(lambda x: x, params)
    p2["moe_layers"]["sinks"] = p2["moe_layers"]["sinks"] + 5.0
    l2, _ = spec.module.forward(p2, cfg, ids)
    assert not np.allclose(np.asarray(logits), np.asarray(l2))

    # HF roundtrip with fused interleaved gate_up + biases + sinks
    adapter = MoEDecoderAdapter(cfg, style="gpt_oss")
    save_hf_checkpoint(adapter.to_hf(params), str(tmp_path))
    reader = HFCheckpointReader(str(tmp_path))
    assert "model.layers.0.mlp.experts.gate_up_proj" in reader.keys()
    assert "model.layers.0.mlp.router.bias" in reader.keys()
    assert "model.layers.1.self_attn.sinks" in reader.keys()
    assert "model.layers.0.self_attn.o_proj.bias" in reader.keys()
    restored = adapter.from_hf(reader)
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(restored)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))


def test_swigluoai_combine():
    from automodel_tpu.moe.experts import gated_combine

    g = jnp.asarray([-2.0, 0.0, 10.0])
    u = jnp.asarray([10.0, 0.5, -10.0])
    out = np.asarray(gated_combine(g, u, "swigluoai"))
    # gate clamped at 7, up clamped to ±7, (u+1) multiplier
    g_c = np.minimum(np.asarray(g), 7.0)
    expect = g_c / (1 + np.exp(-1.702 * g_c)) * (np.clip(np.asarray(u), -7, 7) + 1)
    np.testing.assert_allclose(out, expect, rtol=1e-5)


@pytest.mark.slow
def test_mtp_head_and_loss(tmp_path):
    """DSv3-style MTP: params exist, loss decreases, t+2 shift verified."""
    import dataclasses as dc
    import json

    from automodel_tpu.models.moe_lm.mtp import mtp_hidden, mtp_loss
    from automodel_tpu.cli.app import resolve_recipe_class
    from automodel_tpu.config import ConfigNode

    cfg = dc.replace(MOE_LM, mtp_num_layers=1)
    params = moe_decoder.init(cfg, jax.random.key(0))
    assert "mtp" in params
    specs = moe_decoder.param_specs(cfg)
    assert len(jax.tree.leaves(specs, is_leaf=lambda x: isinstance(x, tuple))) == len(
        jax.tree.leaves(params)
    )

    ids = jax.random.randint(jax.random.key(1), (2, 8), 0, 64)
    labels = jnp.concatenate([ids[:, 1:], jnp.full((2, 1), -100)], axis=1)
    pos = jnp.broadcast_to(jnp.arange(8, dtype=jnp.int32)[None], (2, 8))
    hidden, aux = moe_decoder.forward(params, cfg, ids, return_hidden=True)
    h_mtp = mtp_hidden(params, cfg, hidden, ids, pos, None, lambda x, a: x)
    assert h_mtp.shape == hidden.shape
    ce, n = mtp_loss(h_mtp, params["lm_head"]["kernel"], labels, chunk_size=16)
    # t+2 shift: the last TWO positions carry no mtp supervision
    assert float(n) == 2 * (8 - 2)
    assert np.isfinite(float(ce))

    # recipe trains with the MTP term enabled via hf config
    rcfg = ConfigNode({
        "seed": 3, "auto_resume": False, "run_dir": str(tmp_path),
        "model": {"hf_config": {
            "architectures": ["DeepseekV3ForCausalLM"],
            "vocab_size": 64, "hidden_size": 32, "intermediate_size": 48,
            "num_hidden_layers": 2, "num_attention_heads": 4,
            "num_key_value_heads": 4, "q_lora_rank": 12, "kv_lora_rank": 16,
            "qk_nope_head_dim": 8, "qk_rope_head_dim": 4, "v_head_dim": 8,
            "n_routed_experts": 4, "num_experts_per_tok": 2,
            "moe_intermediate_size": 16, "num_nextn_predict_layers": 1,
        }, "dtype": "float32", "remat_policy": "none"},
        "distributed": {"dp_shard": -1},
        "dataset": {"_target_": "automodel_tpu.datasets.mock.MockDatasetConfig",
                    "num_samples": 32, "seq_len": 16, "vocab_size": 64},
        "dataloader": {"microbatch_size": 8, "grad_acc_steps": 1},
        "optimizer": {"name": "adamw", "lr": 1e-3, "weight_decay": 0.0},
        "lr_scheduler": {"style": "constant", "warmup_steps": 0},
        "step_scheduler": {"max_steps": 3, "ckpt_every_steps": 100},
        "checkpoint": {"enabled": False}, "loss": {"chunk_size": 16},
    })
    r = resolve_recipe_class(rcfg)(rcfg)
    r.setup()
    assert r.model_cfg.mtp_num_layers == 1
    r.run_train_validation_loop()
    recs = [json.loads(l) for l in open(tmp_path / "training.jsonl")]
    assert len(recs) == 3 and all(np.isfinite(x["loss"]) for x in recs)


def test_mtp_masks_document_boundaries():
    import dataclasses as dc

    from automodel_tpu.models.moe_lm.mtp import mtp_loss

    hidden = jnp.zeros((1, 6, 32))
    kernel = jnp.zeros((32, 64))
    labels = jnp.asarray([[1, 2, 3, 4, 5, 6]])
    seg = jnp.asarray([[1, 1, 1, 2, 2, 2]])  # doc boundary at t=3
    _, n = mtp_loss(hidden, kernel, labels, chunk_size=8, segment_ids=seg)
    # positions 0,1 (doc1) and 3,4 (doc2) supervise; t=2 crosses docs, t=5 ends
    assert float(n) == 4


@pytest.mark.slow
def test_dropless_ep_matches_ep1_oracle():
    """EP-distributed dropless dispatch (bucketed A2A, DeepEP semantics —
    reference: moe/megatron/fused_a2a.py:139,238) must match the ep=1
    sort/ragged_dot oracle exactly: same routed output, no drops, grads
    flowing through the all_to_all pair. Includes masked (sentinel) tokens
    and a heavily imbalanced routing."""
    import dataclasses as dc

    from automodel_tpu.moe.experts import (
        experts_forward_dropless,
        experts_forward_dropless_ep,
        init_experts,
    )

    cfg = dc.replace(
        MOE, n_routed_experts=8, experts_per_token=2, dispatcher="dropless"
    )
    H, T = 16, 64
    params = init_experts(cfg, H, jax.random.key(0))
    gate = init_gate(cfg, H, jax.random.key(1))
    x = jax.random.normal(jax.random.key(2), (T, H), jnp.float32)
    mask = jnp.ones((T,), bool).at[-3:].set(False)
    w, idx, _, _ = gate_forward(gate, cfg, x, mask)
    # overwrite half the routing to one expert: imbalance must not drop rows
    idx = idx.at[: T // 2, 0].set(3)

    ref = experts_forward_dropless(params, cfg, x, w, idx)
    for epn in (2, 4):
        ctx = MeshConfig(ep=epn, dp_shard=8 // epn).build()
        xin = jax.device_put(
            x, ctx.sharding(("dp_replicate", "dp_shard", "ep", "cp"), None)
        )
        out = jax.jit(
            lambda p, xx: experts_forward_dropless_ep(p, cfg, xx, w, idx, ctx)
        )(params, xin)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
        )

        def loss_ep(p):
            y = experts_forward_dropless_ep(p, cfg, xin, w, idx, ctx)
            return jnp.sum(y**2)

        def loss_ref(p):
            return jnp.sum(experts_forward_dropless(p, cfg, x, w, idx) ** 2)

        g_ep = jax.jit(jax.grad(loss_ep))(params)
        g_ref = jax.grad(loss_ref)(params)
        for a, b in zip(jax.tree.leaves(g_ep), jax.tree.leaves(g_ref)):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-4
            )


@pytest.mark.slow
def test_dropless_ep_full_decoder_train_step():
    """dispatcher=dropless with ep=2 through the FULL MoE decoder forward
    (mesh_ctx threaded decoder → moe_forward → shard_map dispatch)."""
    import dataclasses as dc

    ctx = MeshConfig(ep=2, dp_shard=2, cp=2).build()
    cfg = dc.replace(MOE_LM, moe=dc.replace(MOE_LM.moe, dispatcher="dropless"))
    params = moe_decoder.init(cfg, jax.random.key(0))
    ids = jnp.asarray(
        np.random.default_rng(0).integers(1, cfg.vocab_size, (4, 8)), jnp.int32
    )
    ids = jax.device_put(ids, ctx.sharding("batch", "cp"))

    def loss(p):
        logits, aux = moe_decoder.forward(p, cfg, ids, mesh_ctx=ctx)
        return jnp.mean(logits**2) + aux

    val, g = jax.jit(jax.value_and_grad(loss))(params)
    assert np.isfinite(float(val))
    for leaf in jax.tree.leaves(g):
        assert np.isfinite(np.asarray(leaf)).all()


@pytest.mark.slow
def test_router_replay_pins_selection():
    """R3 (reference: moe/router_replay.py): capture the routing on one
    forward, replay it on another — selection identical even after the
    router weights change, weights recomputed live, grads flow."""
    params = moe_decoder.init(MOE_LM, jax.random.key(0))
    ids = jax.random.randint(jax.random.key(9), (2, 8), 0, 64)

    _, _, stats = moe_decoder.forward(params, MOE_LM, ids, return_stats=True,
                                      return_routing=True)
    routing = stats["routing"]
    assert routing.shape[0] == MOE_LM.num_moe_layers

    # replay on the same weights: identical logits
    out0, _ = moe_decoder.forward(params, MOE_LM, ids)
    out1, _, st1 = moe_decoder.forward(
        params, MOE_LM, ids, return_stats=True, return_routing=True,
        routing_override=routing,
    )
    np.testing.assert_allclose(np.asarray(out0), np.asarray(out1), rtol=1e-5, atol=1e-5)
    np.testing.assert_array_equal(np.asarray(st1["routing"]), np.asarray(routing))

    # perturb the router weights hard: natural routing changes, replay doesn't
    p2 = jax.tree.map(lambda x: x, params)
    gate = p2["moe_layers"]["moe"]["gate"]
    p2["moe_layers"]["moe"] = {
        **p2["moe_layers"]["moe"],
        "gate": {**gate, "weight": gate["weight"][..., ::-1] * 3.0},
    }
    _, _, nat = moe_decoder.forward(p2, MOE_LM, ids, return_stats=True, return_routing=True)
    assert not np.array_equal(np.asarray(nat["routing"]), np.asarray(routing))
    _, _, rep = moe_decoder.forward(
        p2, MOE_LM, ids, return_stats=True, return_routing=True,
        routing_override=routing,
    )
    np.testing.assert_array_equal(np.asarray(rep["routing"]), np.asarray(routing))

    # gradients still reach the router under replay
    def loss(p):
        out, aux = moe_decoder.forward(p, MOE_LM, ids, routing_override=routing)
        return jnp.mean(out**2) + aux

    g = jax.grad(loss)(params)
    gw = g["moe_layers"]["moe"]["gate"]["weight"]
    assert float(jnp.abs(gw).max()) > 0


def _emulated_ragged_a2a(x, out, in_off, send_sz, out_off, recv_sz, axis_name):
    """CPU emulator of `lax.ragged_all_to_all` semantics (per-shard view),
    built from all_gathers + masked scatters. Test-only: lets the TPU ragged
    EP path run on the virtual-device mesh, where XLA:CPU has no
    ragged-all-to-all thunk."""
    from jax import lax

    P = lax.axis_size(axis_name)
    r = lax.axis_index(axis_name)
    Xall = lax.all_gather(x, axis_name)          # (P, n_in, ...)
    IO = lax.all_gather(in_off, axis_name)       # (P, P)
    SS = lax.all_gather(send_sz, axis_name)      # (P, P)
    OO = lax.all_gather(out_off, axis_name)      # (P, P)
    n_in = x.shape[0]
    idx = jnp.arange(n_in)
    for j in range(P):
        src, io, ss, oo = Xall[j], IO[j, r], SS[j, r], OO[j, r]
        belongs = (idx >= io) & (idx < io + ss)
        pos = jnp.where(belongs, idx - io + oo, out.shape[0])
        out = out.at[pos].set(
            jnp.where(
                belongs.reshape((-1,) + (1,) * (src.ndim - 1)), src, 0
            ),
            mode="drop",
        )
    return out


@pytest.mark.slow
def test_dropless_ep_ragged_matches_dense():
    """The TPU ragged-A2A EP path (metadata: counts all_gather → offsets)
    must route identically to the dense-bucket path — verified on CPU via a
    collective emulator patched over the ragged_all_to_all seam."""
    import dataclasses as dc

    from automodel_tpu.moe import experts as experts_mod
    from automodel_tpu.moe.experts import (
        experts_forward_dropless,
        experts_forward_dropless_ep,
        init_experts,
    )

    cfg = dc.replace(
        MOE, n_routed_experts=8, experts_per_token=2, dispatcher="dropless"
    )
    H, T = 16, 64
    params = init_experts(cfg, H, jax.random.key(0))
    gate = init_gate(cfg, H, jax.random.key(1))
    x = jax.random.normal(jax.random.key(2), (T, H), jnp.float32)
    mask = jnp.ones((T,), bool).at[-3:].set(False)
    w, idx, _, _ = gate_forward(gate, cfg, x, mask)
    idx = idx.at[: T // 2, 0].set(3)  # imbalance

    ref = experts_forward_dropless(params, cfg, x, w, idx)
    orig = experts_mod._raw_ragged_a2a
    experts_mod._raw_ragged_a2a = _emulated_ragged_a2a
    try:
        for epn in (2, 4):
            ctx = MeshConfig(ep=epn, dp_shard=8 // epn).build()
            xin = jax.device_put(
                x, ctx.sharding(("dp_replicate", "dp_shard", "ep", "cp"), None)
            )
            out = jax.jit(
                lambda p, xx: experts_forward_dropless_ep(
                    p, cfg, xx, w, idx, ctx, ragged=True
                )
            )(params, xin)
            np.testing.assert_allclose(
                np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
            )

            def loss_ragged(p):
                y = experts_forward_dropless_ep(
                    p, cfg, xin, w, idx, ctx, ragged=True
                )
                return jnp.sum(y**2)

            def loss_ref(p):
                return jnp.sum(experts_forward_dropless(p, cfg, x, w, idx) ** 2)

            g_r = jax.jit(jax.grad(loss_ragged))(params)
            g_ref = jax.grad(loss_ref)(params)
            for a, b in zip(jax.tree.leaves(g_r), jax.tree.leaves(g_ref)):
                np.testing.assert_allclose(
                    np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-4
                )
    finally:
        experts_mod._raw_ragged_a2a = orig
