"""Numerical parity against Hugging Face transformers (torch CPU).

The strongest correctness oracle available offline: build a tiny HF model,
save its real safetensors checkpoint, load it through this framework's
adapters, and compare logits token-by-token. Covers the model math AND the
checkpoint mapping in one shot (the reference validates the same way via
its parity tests, e.g. tests/functional_tests/models/*parity*).
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")

from automodel_tpu.checkpoint import HFCheckpointReader, get_adapter
from automodel_tpu.models.registry import get_model_spec


def _save_hf_model(model, config, tmp_path):
    model.eval()
    model.save_pretrained(tmp_path, safe_serialization=True)
    with open(tmp_path / "config.json", "w") as f:
        json.dump(json.loads(config.to_json_string()), f)


def _compare(tmp_path, hf_model, input_ids_np, atol=2e-4):
    reader = HFCheckpointReader(str(tmp_path))
    hf_cfg = reader.hf_config()
    spec = get_model_spec(hf_cfg)
    cfg = spec.config_from_hf(hf_cfg, dtype=jnp.float32, remat_policy="none")
    adapter = get_adapter(spec.adapter_name, cfg, **spec.adapter_kwargs)
    params = adapter.from_hf(reader)

    with torch.no_grad():
        ref = hf_model(torch.tensor(input_ids_np)).logits.float().numpy()
    out = spec.module.forward(params, cfg, jnp.asarray(input_ids_np))
    if isinstance(out, tuple):
        out = out[0]
    got = np.asarray(out, np.float32)
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=atol)


def test_llama_logits_match_hf(tmp_path):
    from transformers import LlamaConfig, LlamaForCausalLM

    config = LlamaConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, rope_theta=10000.0, tie_word_embeddings=False,
        attn_implementation="eager",
    )
    torch.manual_seed(0)
    model = LlamaForCausalLM(config)
    _save_hf_model(model, config, tmp_path)
    ids = np.random.default_rng(0).integers(0, 128, (2, 12))
    _compare(tmp_path, model, ids)


def test_qwen2_logits_match_hf(tmp_path):
    from transformers import Qwen2Config, Qwen2ForCausalLM

    config = Qwen2Config(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, tie_word_embeddings=False,
        attn_implementation="eager",
    )
    torch.manual_seed(1)
    model = Qwen2ForCausalLM(config)
    _save_hf_model(model, config, tmp_path)
    ids = np.random.default_rng(1).integers(0, 128, (1, 10))
    _compare(tmp_path, model, ids)


def test_mixtral_logits_match_hf(tmp_path):
    from transformers import MixtralConfig, MixtralForCausalLM

    config = MixtralConfig(
        vocab_size=128, hidden_size=32, intermediate_size=48,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        num_local_experts=4, num_experts_per_tok=2,
        max_position_embeddings=64, tie_word_embeddings=False,
        attn_implementation="eager",
    )
    torch.manual_seed(2)
    model = MixtralForCausalLM(config)
    _save_hf_model(model, config, tmp_path)
    ids = np.random.default_rng(2).integers(0, 128, (1, 8))
    # MoE top-k weighting amplifies tiny fp differences; slightly looser
    _compare(tmp_path, model, ids, atol=5e-4)


def test_qwen3_next_logits_match_hf(tmp_path):
    """Hybrid GDN + gated attention + MoE w/ gated shared expert — the whole
    qwen3-next stack (linear-attention recurrence, causal conv, partial
    RoPE, zero-centered norms) against the HF torch oracle."""
    from transformers import Qwen3NextConfig, Qwen3NextForCausalLM

    config = Qwen3NextConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=4, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16, partial_rotary_factor=0.25,
        layer_types=["linear_attention", "full_attention",
                     "linear_attention", "full_attention"],
        linear_num_value_heads=4, linear_num_key_heads=2,
        linear_key_head_dim=8, linear_value_head_dim=8,
        linear_conv_kernel_dim=4,
        num_experts=4, num_experts_per_tok=2, moe_intermediate_size=32,
        shared_expert_intermediate_size=32, norm_topk_prob=True,
        decoder_sparse_step=1, mlp_only_layers=[],
        max_position_embeddings=64, tie_word_embeddings=False,
        attn_implementation="eager",
    )
    torch.manual_seed(7)
    model = Qwen3NextForCausalLM(config)
    _save_hf_model(model, config, tmp_path)
    ids = np.random.default_rng(7).integers(0, 128, (2, 12))
    _compare(tmp_path, model, ids, atol=5e-4)


def test_llama_bidirectional_loads_and_attends_both_ways(tmp_path):
    """The bidirectional retrieval family (reference:
    models/llama_bidirectional/model.py:79): a llama checkpoint declared as
    LlamaBidirectionalModel loads through the dense adapter with
    causal=False — early positions must depend on later tokens."""
    import dataclasses

    from transformers import LlamaConfig, LlamaForCausalLM

    config = LlamaConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, tie_word_embeddings=False,
        attn_implementation="eager",
    )
    torch.manual_seed(3)
    model = LlamaForCausalLM(config)
    _save_hf_model(model, config, tmp_path)
    # rewrite the saved architecture to the bidirectional family
    cfg_path = tmp_path / "config.json"
    d = json.loads(cfg_path.read_text())
    d["architectures"] = ["LlamaBidirectionalModel"]
    d["pooling"] = "avg"
    cfg_path.write_text(json.dumps(d))

    reader = HFCheckpointReader(str(tmp_path))
    spec = get_model_spec(reader.hf_config())
    assert spec.name == "llama_bidirectional"
    cfg = spec.config_from_hf(reader.hf_config(), dtype=jnp.float32, remat_policy="none")
    assert cfg.causal is False
    params = get_adapter(spec.adapter_name, cfg, **spec.adapter_kwargs).from_hf(reader)

    ids = np.random.default_rng(3).integers(0, 128, (1, 10))
    h1 = spec.module.forward(params, cfg, jnp.asarray(ids), return_hidden=True)
    ids2 = ids.copy()
    ids2[0, -1] = (ids2[0, -1] + 1) % 128
    h2 = spec.module.forward(params, cfg, jnp.asarray(ids2), return_hidden=True)
    # bidirectional: the first position changes when the last token changes
    assert float(jnp.abs(h1[0, 0] - h2[0, 0]).max()) > 1e-6
    # and the causal variant would not
    ccfg = dataclasses.replace(cfg, causal=True)
    c1 = spec.module.forward(params, ccfg, jnp.asarray(ids), return_hidden=True)
    c2 = spec.module.forward(params, ccfg, jnp.asarray(ids2), return_hidden=True)
    np.testing.assert_allclose(
        np.asarray(c1[0, 0]), np.asarray(c2[0, 0]), rtol=1e-5, atol=1e-6
    )


def test_qwen3_next_sharded_matches_single_device():
    """GDN scan + conv + MoE under a dp×ep mesh vs single device."""
    from automodel_tpu.distributed import MeshConfig
    from automodel_tpu.models.hybrid import qwen3_next as q3n
    from automodel_tpu.parallel import logical_to_shardings

    hf = dict(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16, layer_types=["linear_attention", "full_attention"],
        linear_num_value_heads=4, linear_num_key_heads=2,
        linear_key_head_dim=8, linear_value_head_dim=8,
        num_experts=4, num_experts_per_tok=2, moe_intermediate_size=32,
        shared_expert_intermediate_size=32,
    )
    cfg = q3n.from_hf_config(hf, dtype=jnp.float32, remat_policy="none")
    params = q3n.init(cfg, jax.random.key(0))
    ids = jnp.asarray(np.random.default_rng(5).integers(0, 128, (8, 8)), jnp.int32)
    ref, ref_aux = q3n.forward(params, cfg, ids)

    ctx = MeshConfig(dp_shard=2, ep=2, tp=2).build()
    sh = logical_to_shardings(
        q3n.param_specs(cfg), ctx, shapes=jax.tree.map(lambda p: p.shape, params)
    )
    sp = jax.device_put(params, sh)
    out, aux = jax.jit(lambda p, i: q3n.forward(p, cfg, i, mesh_ctx=ctx))(
        sp, jax.device_put(ids, ctx.sharding("batch", None))
    )
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out), rtol=2e-3, atol=2e-3)
    np.testing.assert_allclose(float(ref_aux), float(aux), rtol=1e-4, atol=1e-6)


def test_mamba2_logits_match_hf(tmp_path):
    """Mamba2 SSD mixer (conv + selective scan + gated norm) vs the HF
    torch oracle's naive SSD path."""
    from transformers import Mamba2Config, Mamba2ForCausalLM

    config = Mamba2Config(
        vocab_size=128, hidden_size=32, num_hidden_layers=2,
        state_size=16, num_heads=4, head_dim=16, n_groups=2,
        conv_kernel=4, expand=2, use_conv_bias=True, use_bias=False,
        tie_word_embeddings=False,  # HF save_pretrained chokes on mamba2 tying
    )
    torch.manual_seed(11)
    model = Mamba2ForCausalLM(config)
    _save_hf_model(model, config, tmp_path)
    ids = np.random.default_rng(11).integers(0, 128, (2, 12))
    _compare(tmp_path, model, ids, atol=5e-4)


def test_mamba2_segment_isolation_and_roundtrip(tmp_path):
    """Packed docs: the SSM state and conv window reset at segment heads —
    per-document outputs equal running each document alone. Plus a
    to_hf→from_hf roundtrip."""
    from automodel_tpu.models.hybrid import mamba2 as m2

    hf = dict(
        vocab_size=64, hidden_size=32, num_hidden_layers=2, state_size=8,
        num_heads=4, head_dim=16, n_groups=2, conv_kernel=4,
        tie_word_embeddings=True,
    )
    cfg = m2.from_hf_config(hf, dtype=jnp.float32, remat_policy="none")
    params = m2.init(cfg, jax.random.key(0))
    rng = np.random.default_rng(2)
    a = jnp.asarray(rng.integers(1, 64, (1, 6)), jnp.int32)
    b = jnp.asarray(rng.integers(1, 64, (1, 10)), jnp.int32)
    packed = jnp.concatenate([a, b], axis=1)
    seg = jnp.asarray([[0] * 6 + [1] * 10], jnp.int32)

    out_packed = m2.forward(params, cfg, packed, segment_ids=seg)
    out_a = m2.forward(params, cfg, a)
    out_b = m2.forward(params, cfg, b)
    np.testing.assert_allclose(
        np.asarray(out_packed[:, :6]), np.asarray(out_a), rtol=1e-4, atol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(out_packed[:, 6:]), np.asarray(out_b), rtol=1e-4, atol=1e-5
    )

    # adapter roundtrip: to_hf → dict reader → from_hf → identical logits
    adapter = m2.Mamba2Adapter(cfg)
    sd = {k: v for k, v in adapter.to_hf(params)}
    params2 = adapter.from_hf(lambda name: sd[name])
    out2 = m2.forward(params2, cfg, a)
    np.testing.assert_allclose(np.asarray(out_a), np.asarray(out2), rtol=1e-6)
