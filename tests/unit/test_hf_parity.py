"""Numerical parity against Hugging Face transformers (torch CPU).

The strongest correctness oracle available offline: build a tiny HF model,
save its real safetensors checkpoint, load it through this framework's
adapters, and compare logits token-by-token. Covers the model math AND the
checkpoint mapping in one shot (the reference validates the same way via
its parity tests, e.g. tests/functional_tests/models/*parity*).
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")

from automodel_tpu.checkpoint import HFCheckpointReader, get_adapter
from automodel_tpu.models.registry import get_model_spec


def _save_hf_model(model, config, tmp_path):
    model.eval()
    model.save_pretrained(tmp_path, safe_serialization=True)
    with open(tmp_path / "config.json", "w") as f:
        json.dump(json.loads(config.to_json_string()), f)


def _compare(tmp_path, hf_model, input_ids_np, atol=2e-4):
    reader = HFCheckpointReader(str(tmp_path))
    hf_cfg = reader.hf_config()
    spec = get_model_spec(hf_cfg)
    cfg = spec.config_from_hf(hf_cfg, dtype=jnp.float32, remat_policy="none")
    adapter = get_adapter(spec.adapter_name, cfg, **spec.adapter_kwargs)
    params = adapter.from_hf(reader)

    with torch.no_grad():
        ref = hf_model(torch.tensor(input_ids_np)).logits.float().numpy()
    out = spec.module.forward(params, cfg, jnp.asarray(input_ids_np))
    if isinstance(out, tuple):
        out = out[0]
    got = np.asarray(out, np.float32)
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=atol)


def test_llama_logits_match_hf(tmp_path):
    from transformers import LlamaConfig, LlamaForCausalLM

    config = LlamaConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, rope_theta=10000.0, tie_word_embeddings=False,
        attn_implementation="eager",
    )
    torch.manual_seed(0)
    model = LlamaForCausalLM(config)
    _save_hf_model(model, config, tmp_path)
    ids = np.random.default_rng(0).integers(0, 128, (2, 12))
    _compare(tmp_path, model, ids)


def test_qwen2_logits_match_hf(tmp_path):
    from transformers import Qwen2Config, Qwen2ForCausalLM

    config = Qwen2Config(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, tie_word_embeddings=False,
        attn_implementation="eager",
    )
    torch.manual_seed(1)
    model = Qwen2ForCausalLM(config)
    _save_hf_model(model, config, tmp_path)
    ids = np.random.default_rng(1).integers(0, 128, (1, 10))
    _compare(tmp_path, model, ids)


def test_mixtral_logits_match_hf(tmp_path):
    from transformers import MixtralConfig, MixtralForCausalLM

    config = MixtralConfig(
        vocab_size=128, hidden_size=32, intermediate_size=48,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        num_local_experts=4, num_experts_per_tok=2,
        max_position_embeddings=64, tie_word_embeddings=False,
        attn_implementation="eager",
    )
    torch.manual_seed(2)
    model = MixtralForCausalLM(config)
    _save_hf_model(model, config, tmp_path)
    ids = np.random.default_rng(2).integers(0, 128, (1, 8))
    # MoE top-k weighting amplifies tiny fp differences; slightly looser
    _compare(tmp_path, model, ids, atol=5e-4)
