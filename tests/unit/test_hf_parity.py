"""Numerical parity against Hugging Face transformers (torch CPU).

The strongest correctness oracle available offline: build a tiny HF model,
save its real safetensors checkpoint, load it through this framework's
adapters, and compare logits token-by-token. Covers the model math AND the
checkpoint mapping in one shot (the reference validates the same way via
its parity tests, e.g. tests/functional_tests/models/*parity*).
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.parity

torch = pytest.importorskip("torch")

from automodel_tpu.checkpoint import HFCheckpointReader, get_adapter
from automodel_tpu.models.registry import get_model_spec


def _save_hf_model(model, config, tmp_path):
    model.eval()
    model.save_pretrained(tmp_path, safe_serialization=True)
    with open(tmp_path / "config.json", "w") as f:
        json.dump(json.loads(config.to_json_string()), f)


def _compare(tmp_path, hf_model, input_ids_np, atol=2e-4):
    reader = HFCheckpointReader(str(tmp_path))
    hf_cfg = reader.hf_config()
    spec = get_model_spec(hf_cfg)
    cfg = spec.config_from_hf(hf_cfg, dtype=jnp.float32, remat_policy="none")
    adapter = get_adapter(spec.adapter_name, cfg, **spec.adapter_kwargs)
    params = adapter.from_hf(reader)

    with torch.no_grad():
        ref = hf_model(torch.tensor(input_ids_np)).logits.float().numpy()
    out = spec.module.forward(params, cfg, jnp.asarray(input_ids_np))
    if isinstance(out, tuple):
        out = out[0]
    got = np.asarray(out, np.float32)
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=atol)


def test_llama_logits_match_hf(tmp_path):
    from transformers import LlamaConfig, LlamaForCausalLM

    config = LlamaConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, rope_theta=10000.0, tie_word_embeddings=False,
        attn_implementation="eager",
    )
    torch.manual_seed(0)
    model = LlamaForCausalLM(config)
    _save_hf_model(model, config, tmp_path)
    ids = np.random.default_rng(0).integers(0, 128, (2, 12))
    _compare(tmp_path, model, ids)


def test_qwen2_logits_match_hf(tmp_path):
    from transformers import Qwen2Config, Qwen2ForCausalLM

    config = Qwen2Config(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, tie_word_embeddings=False,
        attn_implementation="eager",
    )
    torch.manual_seed(1)
    model = Qwen2ForCausalLM(config)
    _save_hf_model(model, config, tmp_path)
    ids = np.random.default_rng(1).integers(0, 128, (1, 10))
    _compare(tmp_path, model, ids)


def test_mixtral_logits_match_hf(tmp_path):
    from transformers import MixtralConfig, MixtralForCausalLM

    config = MixtralConfig(
        vocab_size=128, hidden_size=32, intermediate_size=48,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        num_local_experts=4, num_experts_per_tok=2,
        max_position_embeddings=64, tie_word_embeddings=False,
        attn_implementation="eager",
    )
    torch.manual_seed(2)
    model = MixtralForCausalLM(config)
    _save_hf_model(model, config, tmp_path)
    ids = np.random.default_rng(2).integers(0, 128, (1, 8))
    # MoE top-k weighting amplifies tiny fp differences; slightly looser
    _compare(tmp_path, model, ids, atol=5e-4)


def test_qwen3_next_logits_match_hf(tmp_path):
    """Hybrid GDN + gated attention + MoE w/ gated shared expert — the whole
    qwen3-next stack (linear-attention recurrence, causal conv, partial
    RoPE, zero-centered norms) against the HF torch oracle."""
    from transformers import Qwen3NextConfig, Qwen3NextForCausalLM

    config = Qwen3NextConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=4, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16, partial_rotary_factor=0.25,
        layer_types=["linear_attention", "full_attention",
                     "linear_attention", "full_attention"],
        linear_num_value_heads=4, linear_num_key_heads=2,
        linear_key_head_dim=8, linear_value_head_dim=8,
        linear_conv_kernel_dim=4,
        num_experts=4, num_experts_per_tok=2, moe_intermediate_size=32,
        shared_expert_intermediate_size=32, norm_topk_prob=True,
        decoder_sparse_step=1, mlp_only_layers=[],
        max_position_embeddings=64, tie_word_embeddings=False,
        attn_implementation="eager",
    )
    torch.manual_seed(7)
    model = Qwen3NextForCausalLM(config)
    _save_hf_model(model, config, tmp_path)
    ids = np.random.default_rng(7).integers(0, 128, (2, 12))
    _compare(tmp_path, model, ids, atol=5e-4)


def test_glm4_logits_match_hf(tmp_path):
    """GLM-4 dense: partial INTERLEAVED rotary, sandwich norms, fused
    gate_up MLP (adapter style glm4)."""
    from transformers import Glm4Config, Glm4ForCausalLM

    config = Glm4Config(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        partial_rotary_factor=0.5, attention_bias=True,
        max_position_embeddings=64, tie_word_embeddings=False,
        pad_token_id=0, eos_token_id=1, attn_implementation="eager",
    )
    torch.manual_seed(21)
    model = Glm4ForCausalLM(config)
    _save_hf_model(model, config, tmp_path)
    ids = np.random.default_rng(21).integers(0, 128, (2, 10))
    _compare(tmp_path, model, ids)


def test_glm4_moe_logits_match_hf(tmp_path):
    """GLM-4.5 MoE: sigmoid grouped router + e-score bias + shared expert +
    first-k-dense on partial-rotary GQA with qk-norm."""
    from transformers import Glm4MoeConfig, Glm4MoeForCausalLM

    config = Glm4MoeConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=3, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16, partial_rotary_factor=0.5, use_qk_norm=True,
        n_routed_experts=4, num_experts_per_tok=2, moe_intermediate_size=32,
        n_shared_experts=1, first_k_dense_replace=1, n_group=2, topk_group=1,
        norm_topk_prob=True, routed_scaling_factor=1.5,
        max_position_embeddings=64, tie_word_embeddings=False,
        attn_implementation="eager",
    )
    torch.manual_seed(22)
    model = Glm4MoeForCausalLM(config)
    # give the e-score bias real values so the selection path is exercised
    with torch.no_grad():
        for layer in model.model.layers[1:]:
            layer.mlp.gate.e_score_correction_bias.uniform_(-0.05, 0.05)
    _save_hf_model(model, config, tmp_path)
    ids = np.random.default_rng(22).integers(0, 128, (2, 8))
    _compare(tmp_path, model, ids, atol=5e-4)


def test_ernie4_5_logits_match_hf(tmp_path):
    from transformers import Ernie4_5Config, Ernie4_5ForCausalLM

    config = Ernie4_5Config(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=8, use_bias=True, max_position_embeddings=64,
        tie_word_embeddings=True, attn_implementation="eager",
    )
    torch.manual_seed(23)
    model = Ernie4_5ForCausalLM(config)
    _save_hf_model(model, config, tmp_path)
    ids = np.random.default_rng(23).integers(0, 128, (1, 9))
    _compare(tmp_path, model, ids)


def test_ernie4_5_moe_logits_match_hf(tmp_path):
    """ERNIE-4.5 MoE: softmax scores with the moe_statics correction bias
    applied for selection only, fused shared-experts MLP, first dense
    layer via moe_layer_start_index."""
    from transformers import Ernie4_5_MoeConfig, Ernie4_5_MoeForCausalLM

    config = Ernie4_5_MoeConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=3, num_attention_heads=4, num_key_value_heads=2,
        head_dim=8, moe_num_experts=4, moe_k=2, moe_intermediate_size=32,
        moe_num_shared_experts=1, moe_layer_start_index=1,
        moe_layer_interval=1, moe_layer_end_index=2,
        max_position_embeddings=64, tie_word_embeddings=False,
        attn_implementation="eager",
    )
    torch.manual_seed(24)
    model = Ernie4_5_MoeForCausalLM(config)
    with torch.no_grad():
        for layer in model.model.layers[1:]:
            layer.mlp.moe_statics.e_score_correction_bias.uniform_(-0.05, 0.05)
    _save_hf_model(model, config, tmp_path)
    ids = np.random.default_rng(24).integers(0, 128, (2, 8))
    _compare(tmp_path, model, ids, atol=5e-4)


def test_gemma3_logits_match_hf(tmp_path):
    """Gemma3 text: qk-norm + zero-centered sandwich norms + 5:1
    sliding/global pattern with a SEPARATE local rope theta on sliding
    layers (rope_local_base_freq)."""
    from transformers import Gemma3ForCausalLM, Gemma3TextConfig

    config = Gemma3TextConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=4, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16, query_pre_attn_scalar=16,
        sliding_window=4, layer_types=[
            "sliding_attention", "sliding_attention",
            "full_attention", "sliding_attention",
        ],
        rope_theta=1_000_000.0, rope_local_base_freq=10_000.0,
        max_position_embeddings=64, tie_word_embeddings=True,
        attn_implementation="eager",
    )
    torch.manual_seed(25)
    model = Gemma3ForCausalLM(config)
    _save_hf_model(model, config, tmp_path)
    ids = np.random.default_rng(25).integers(0, 128, (2, 12))
    _compare(tmp_path, model, ids, atol=5e-4)


def test_hunyuan_dense_logits_match_hf(tmp_path):
    """HunYuan dense: per-head qk-norm applied AFTER rotary."""
    from transformers import HunYuanDenseV1Config, HunYuanDenseV1ForCausalLM

    config = HunYuanDenseV1Config(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=8, max_position_embeddings=64, tie_word_embeddings=False,
        pad_token_id=0, eos_token_id=1, attn_implementation="eager",
    )
    torch.manual_seed(27)
    model = HunYuanDenseV1ForCausalLM(config)
    _save_hf_model(model, config, tmp_path)
    ids = np.random.default_rng(27).integers(0, 128, (2, 10))
    _compare(tmp_path, model, ids)


def test_hunyuan_moe_logits_match_hf(tmp_path):
    """HunYuan MoE: softmax top-k router + always-on shared MLP with the
    gate at mlp.gate.wg and shared experts at mlp.shared_mlp."""
    from transformers import HunYuanMoEV1Config, HunYuanMoEV1ForCausalLM

    config = HunYuanMoEV1Config(
        vocab_size=128, hidden_size=32, intermediate_size=32,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        num_experts=4, moe_topk=2, head_dim=8,
        max_position_embeddings=64, tie_word_embeddings=False,
        pad_token_id=0, eos_token_id=1, attn_implementation="eager",
    )
    torch.manual_seed(28)
    model = HunYuanMoEV1ForCausalLM(config)
    _save_hf_model(model, config, tmp_path)
    ids = np.random.default_rng(28).integers(0, 128, (2, 8))
    _compare(tmp_path, model, ids, atol=5e-4)


def test_minimax_m2_adapter_roundtrip():
    """MiniMax-M2 (no torch class in this transformers build): flat qk-norm
    + partial rotary + e-score-biased MoE through a full to_hf → from_hf
    adapter roundtrip with mixtral-style block_sparse_moe names."""
    from automodel_tpu.checkpoint.hf_adapter import get_adapter
    from automodel_tpu.models.registry import get_model_spec

    hf = dict(
        architectures=["MiniMaxM2ForCausalLM"],
        vocab_size=128, hidden_size=32, intermediate_size=32,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16, rotary_dim=8, use_qk_norm=True, scoring_func="sigmoid",
        num_local_experts=4, num_experts_per_tok=2,
        max_position_embeddings=64,
    )
    spec = get_model_spec(hf)
    cfg = spec.config_from_hf(hf, dtype=jnp.float32, remat_policy="none")
    assert cfg.qk_norm_flat and abs(cfg.partial_rotary_factor - 0.5) < 1e-9
    params = spec.module.init(cfg, jax.random.key(3))
    adapter = get_adapter(spec.adapter_name, cfg, **spec.adapter_kwargs)
    sd = dict(adapter.to_hf(params))
    assert "model.layers.1.block_sparse_moe.experts.0.w1.weight" in sd
    assert "model.layers.1.block_sparse_moe.e_score_correction_bias" in sd

    def read(name):
        if name not in sd:
            raise KeyError(name)
        return sd[name]

    params2 = adapter.from_hf(read)
    ids = jnp.asarray(np.random.default_rng(26).integers(0, 128, (1, 8)))
    out1, _ = spec.module.forward(params, cfg, ids)
    out2, _ = spec.module.forward(params2, cfg, ids)
    np.testing.assert_allclose(np.asarray(out1), np.asarray(out2), rtol=1e-6)


def test_llama_bidirectional_loads_and_attends_both_ways(tmp_path):
    """The bidirectional retrieval family (reference:
    models/llama_bidirectional/model.py:79): a llama checkpoint declared as
    LlamaBidirectionalModel loads through the dense adapter with
    causal=False — early positions must depend on later tokens."""
    import dataclasses

    from transformers import LlamaConfig, LlamaForCausalLM

    config = LlamaConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, tie_word_embeddings=False,
        attn_implementation="eager",
    )
    torch.manual_seed(3)
    model = LlamaForCausalLM(config)
    _save_hf_model(model, config, tmp_path)
    # rewrite the saved architecture to the bidirectional family
    cfg_path = tmp_path / "config.json"
    d = json.loads(cfg_path.read_text())
    d["architectures"] = ["LlamaBidirectionalModel"]
    d["pooling"] = "avg"
    cfg_path.write_text(json.dumps(d))

    reader = HFCheckpointReader(str(tmp_path))
    spec = get_model_spec(reader.hf_config())
    assert spec.name == "llama_bidirectional"
    cfg = spec.config_from_hf(reader.hf_config(), dtype=jnp.float32, remat_policy="none")
    assert cfg.causal is False
    params = get_adapter(spec.adapter_name, cfg, **spec.adapter_kwargs).from_hf(reader)

    ids = np.random.default_rng(3).integers(0, 128, (1, 10))
    h1 = spec.module.forward(params, cfg, jnp.asarray(ids), return_hidden=True)
    ids2 = ids.copy()
    ids2[0, -1] = (ids2[0, -1] + 1) % 128
    h2 = spec.module.forward(params, cfg, jnp.asarray(ids2), return_hidden=True)
    # bidirectional: the first position changes when the last token changes
    assert float(jnp.abs(h1[0, 0] - h2[0, 0]).max()) > 1e-6
    # and the causal variant would not
    ccfg = dataclasses.replace(cfg, causal=True)
    c1 = spec.module.forward(params, ccfg, jnp.asarray(ids), return_hidden=True)
    c2 = spec.module.forward(params, ccfg, jnp.asarray(ids2), return_hidden=True)
    np.testing.assert_allclose(
        np.asarray(c1[0, 0]), np.asarray(c2[0, 0]), rtol=1e-5, atol=1e-6
    )


def test_qwen3_next_sharded_matches_single_device():
    """GDN scan + conv + MoE under a dp×ep mesh vs single device."""
    from automodel_tpu.distributed import MeshConfig
    from automodel_tpu.models.hybrid import qwen3_next as q3n
    from automodel_tpu.parallel import logical_to_shardings

    hf = dict(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16, layer_types=["linear_attention", "full_attention"],
        linear_num_value_heads=4, linear_num_key_heads=2,
        linear_key_head_dim=8, linear_value_head_dim=8,
        num_experts=4, num_experts_per_tok=2, moe_intermediate_size=32,
        shared_expert_intermediate_size=32,
    )
    cfg = q3n.from_hf_config(hf, dtype=jnp.float32, remat_policy="none")
    params = q3n.init(cfg, jax.random.key(0))
    ids = jnp.asarray(np.random.default_rng(5).integers(0, 128, (8, 8)), jnp.int32)
    ref, ref_aux = q3n.forward(params, cfg, ids)

    ctx = MeshConfig(dp_shard=2, ep=2, tp=2).build()
    sh = logical_to_shardings(
        q3n.param_specs(cfg), ctx, shapes=jax.tree.map(lambda p: p.shape, params)
    )
    sp = jax.device_put(params, sh)
    out, aux = jax.jit(lambda p, i: q3n.forward(p, cfg, i, mesh_ctx=ctx))(
        sp, jax.device_put(ids, ctx.sharding("batch", None))
    )
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out), rtol=2e-3, atol=2e-3)
    np.testing.assert_allclose(float(ref_aux), float(aux), rtol=1e-4, atol=1e-6)


def test_mamba2_logits_match_hf(tmp_path):
    """Mamba2 SSD mixer (conv + selective scan + gated norm) vs the HF
    torch oracle's naive SSD path."""
    from transformers import Mamba2Config, Mamba2ForCausalLM

    config = Mamba2Config(
        vocab_size=128, hidden_size=32, num_hidden_layers=2,
        state_size=16, num_heads=4, head_dim=16, n_groups=2,
        conv_kernel=4, expand=2, use_conv_bias=True, use_bias=False,
        tie_word_embeddings=False,  # HF save_pretrained chokes on mamba2 tying
    )
    torch.manual_seed(11)
    model = Mamba2ForCausalLM(config)
    _save_hf_model(model, config, tmp_path)
    ids = np.random.default_rng(11).integers(0, 128, (2, 12))
    _compare(tmp_path, model, ids, atol=5e-4)


def test_mamba2_segment_isolation_and_roundtrip(tmp_path):
    """Packed docs: the SSM state and conv window reset at segment heads —
    per-document outputs equal running each document alone. Plus a
    to_hf→from_hf roundtrip."""
    from automodel_tpu.models.hybrid import mamba2 as m2

    hf = dict(
        vocab_size=64, hidden_size=32, num_hidden_layers=2, state_size=8,
        num_heads=4, head_dim=16, n_groups=2, conv_kernel=4,
        tie_word_embeddings=True,
    )
    cfg = m2.from_hf_config(hf, dtype=jnp.float32, remat_policy="none")
    params = m2.init(cfg, jax.random.key(0))
    rng = np.random.default_rng(2)
    a = jnp.asarray(rng.integers(1, 64, (1, 6)), jnp.int32)
    b = jnp.asarray(rng.integers(1, 64, (1, 10)), jnp.int32)
    packed = jnp.concatenate([a, b], axis=1)
    seg = jnp.asarray([[0] * 6 + [1] * 10], jnp.int32)

    out_packed = m2.forward(params, cfg, packed, segment_ids=seg)
    out_a = m2.forward(params, cfg, a)
    out_b = m2.forward(params, cfg, b)
    np.testing.assert_allclose(
        np.asarray(out_packed[:, :6]), np.asarray(out_a), rtol=1e-4, atol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(out_packed[:, 6:]), np.asarray(out_b), rtol=1e-4, atol=1e-5
    )

    # adapter roundtrip: to_hf → dict reader → from_hf → identical logits
    adapter = m2.Mamba2Adapter(cfg)
    sd = {k: v for k, v in adapter.to_hf(params)}
    params2 = adapter.from_hf(lambda name: sd[name])
    out2 = m2.forward(params2, cfg, a)
    np.testing.assert_allclose(np.asarray(out_a), np.asarray(out2), rtol=1e-6)
