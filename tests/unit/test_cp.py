"""Context-parallel ring attention parity tests (8-device virtual mesh)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from automodel_tpu.distributed import MeshConfig
from automodel_tpu.models.llm import decoder
from automodel_tpu.models.llm.decoder import TransformerConfig
from automodel_tpu.ops.attention import make_attention_mask, xla_attention
from automodel_tpu.parallel import logical_to_shardings
from automodel_tpu.parallel.cp import (
    ContextParallelSharder,
    load_balanced_permutation,
    ring_dot_product_attention,
)


def _qkv(key, B=2, S=64, Hq=4, Hkv=2, D=16):
    ks = jax.random.split(key, 3)
    return (
        jax.random.normal(ks[0], (B, S, Hq, D)),
        jax.random.normal(ks[1], (B, S, Hkv, D)),
        jax.random.normal(ks[2], (B, S, Hkv, D)),
    )


def test_load_balanced_permutation_props():
    perm = load_balanced_permutation(32, 4)
    assert sorted(perm.tolist()) == list(range(32))
    # rank 0 owns chunks 0 and 7
    assert perm[:4].tolist() == [0, 1, 2, 3]
    assert perm[4:8].tolist() == [28, 29, 30, 31]


def test_sharder_contract():
    sh = ContextParallelSharder(cp_size=4)
    batch = {
        "input_ids": np.arange(32)[None, :].repeat(2, 0),
        "labels": np.arange(32)[None, :].repeat(2, 0),
    }
    out = sh.shard_batch(batch)
    assert "positions" in out
    # positions equal the permuted global indices
    np.testing.assert_array_equal(out["positions"][0], out["input_ids"][0])
    idx0 = sh.local_token_global_indices(32, 0)
    np.testing.assert_array_equal(idx0, out["positions"][0][:8])


@pytest.mark.slow
@pytest.mark.parametrize("cp", [2, 4])
@pytest.mark.parametrize("balanced", [False, True])
def test_ring_attention_matches_oracle(cp, balanced):
    ctx = MeshConfig(cp=cp, dp_shard=8 // cp).build()
    q, k, v = _qkv(jax.random.key(0), B=8 // cp, S=64)
    S = 64
    perm = (
        load_balanced_permutation(S, cp) if balanced else np.arange(S)
    )
    positions = jnp.asarray(perm, jnp.int32)[None, :].repeat(q.shape[0], 0)
    qp, kp, vp = q[:, perm], k[:, perm], v[:, perm]

    @jax.jit
    def ring(q, k, v, pos):
        return ring_dot_product_attention(q, k, v, pos, None, ctx, causal=True)

    out = ring(
        jax.device_put(qp, ctx.sharding("batch", "cp", None, None)),
        jax.device_put(kp, ctx.sharding("batch", "cp", None, None)),
        jax.device_put(vp, ctx.sharding("batch", "cp", None, None)),
        jax.device_put(positions, ctx.sharding("batch", "cp")),
    )
    ref = xla_attention(q, k, v, mask=make_attention_mask(S, S, causal=True))
    # un-permute the ring output back to natural order before comparing
    inv = np.argsort(perm)
    np.testing.assert_allclose(
        np.asarray(out)[:, inv], np.asarray(ref), rtol=2e-4, atol=2e-4
    )


@pytest.mark.slow
def test_ring_attention_grads_match():
    cp = 4
    ctx = MeshConfig(cp=cp, dp_shard=2).build()
    q, k, v = _qkv(jax.random.key(1), B=2, S=64)
    S = 64
    positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32)[None, :], (2, S))

    def loss_ring(q, k, v):
        return jnp.sum(ring_dot_product_attention(q, k, v, positions, None, ctx) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(
            xla_attention(q, k, v, mask=make_attention_mask(S, S, causal=True)) ** 2
        )

    g1 = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-3, atol=1e-3)


def test_ring_attention_packed_segments():
    cp = 2
    ctx = MeshConfig(cp=cp, dp_shard=4).build()
    q, k, v = _qkv(jax.random.key(2), B=4, S=64)
    S = 64
    seg = jnp.concatenate(
        [jnp.zeros((4, 24), jnp.int32), jnp.ones((4, 40), jnp.int32)], axis=1
    )
    pos = jnp.concatenate(
        [jnp.arange(24)[None].repeat(4, 0), jnp.arange(40)[None].repeat(4, 0)], axis=1
    ).astype(jnp.int32)

    @jax.jit
    def ring(q, k, v):
        return ring_dot_product_attention(q, k, v, pos, seg, ctx, causal=True)

    out = ring(q, k, v)
    mask = make_attention_mask(
        S, S, causal=True, q_segment_ids=seg, kv_segment_ids=seg,
        q_positions=pos, kv_positions=pos,
    )
    ref = xla_attention(q, k, v, mask=mask)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4)


@pytest.mark.slow
def test_decoder_with_cp_matches_single_device():
    """Full decoder forward under cp=2 (ring path) == single-device."""
    cfg = TransformerConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64, num_layers=2,
        num_heads=4, num_kv_heads=2, dtype=jnp.float32, remat_policy="none",
    )
    ctx = MeshConfig(dp_shard=2, tp=2, cp=2).build()
    params = decoder.init(cfg, jax.random.key(0))
    ids = jax.random.randint(jax.random.key(5), (4, 64), 0, 128)
    ref = decoder.forward(params, cfg, ids)

    shardings = logical_to_shardings(
        decoder.param_specs(cfg), ctx, shapes=jax.tree.map(lambda p: p.shape, params)
    )
    sp = jax.device_put(params, shardings)

    @jax.jit
    def f(p, i):
        return decoder.forward(p, cfg, i, mesh_ctx=ctx)

    out = f(sp, jax.device_put(ids, ctx.sharding("batch", "cp")))
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out), rtol=5e-4, atol=5e-4)


@pytest.mark.slow
def test_ring_flash_kernel_parity():
    """cp=2 ring where each shard's S_loc (128) engages the Pallas flash
    kernel (position-causal mode, interpret on CPU) — fwd + grads vs cp=1."""
    cp = 2
    ctx = MeshConfig(cp=cp, dp_shard=4).build()
    S = 256  # S_loc = 128 per rank → _flash_ring_ok holds
    q, k, v = _qkv(jax.random.key(5), B=4, S=S, Hq=2, Hkv=1, D=128)
    positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32)[None, :], (4, S))

    def loss_ring(q, k, v):
        return jnp.sum(
            ring_dot_product_attention(
                q, k, v, positions, None, ctx, attn_impl="flash"
            ) ** 2
        )

    def loss_ref(q, k, v):
        return jnp.sum(
            xla_attention(q, k, v, mask=make_attention_mask(S, S, causal=True)) ** 2
        )

    out = jax.jit(
        lambda q, k, v: ring_dot_product_attention(
            q, k, v, positions, None, ctx, attn_impl="flash"
        )
    )(q, k, v)
    ref = xla_attention(q, k, v, mask=make_attention_mask(S, S, causal=True))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4)

    g1 = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, n in zip(g1, g2, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-3, atol=2e-3, err_msg=f"d{n}"
        )


def test_ring_attention_with_sinks():
    """gpt-oss sinks under CP: the sink joins the softmax denominator once
    globally; parity vs the single-device XLA sink path."""
    cp = 4
    ctx = MeshConfig(cp=cp, dp_shard=2).build()
    S = 64
    q, k, v = _qkv(jax.random.key(6), B=2, S=S)
    positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32)[None, :], (2, S))
    sinks = jax.random.normal(jax.random.key(7), (4,))

    out = jax.jit(
        lambda q, k, v, s: ring_dot_product_attention(
            q, k, v, positions, None, ctx, sinks=s
        )
    )(q, k, v, sinks)
    ref = xla_attention(
        q, k, v, mask=make_attention_mask(S, S, causal=True), sinks=sinks
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4)


# ---------------------------------------------------------------------------
# per-document (blockdiag) CP
# ---------------------------------------------------------------------------
def test_document_pack_permutation_props():
    """Bijection; whole documents contiguous on one rank; capacity honored;
    oversize documents rejected with the ring-layout pointer."""
    from automodel_tpu.parallel.cp import document_pack_permutation

    seg = np.asarray([0] * 10 + [1] * 6 + [2] * 10 + [3] * 6)  # S=32, cp=2
    perm = document_pack_permutation(seg, 2)
    assert sorted(perm) == list(range(32))
    placed = seg[perm]
    cap = 16
    for r in range(2):
        shard = placed[r * cap : (r + 1) * cap]
        # each doc id appears in exactly one rank and contiguously
        for d in set(shard):
            idx = np.nonzero(placed == d)[0]
            assert idx[0] // cap == idx[-1] // cap          # one rank
            assert (np.diff(idx) == 1).all()                # contiguous
    # two 10-token docs must land on different ranks (capacity 16)
    r10a = np.nonzero(placed == 0)[0][0] // cap
    r10b = np.nonzero(placed == 2)[0][0] // cap
    assert r10a != r10b

    with pytest.raises(ValueError, match="ring handles documents"):
        document_pack_permutation(np.zeros(32, np.int64), 2)  # one 32-doc


def test_blockdiag_local_equals_ring_on_packed():
    """Blockdiag layout + LOCAL attention == ring attention on the same
    packed content: per-token outputs match after inverting the layout."""
    from automodel_tpu.parallel.cp import (
        BlockDiagContextParallelSharder,
        local_cp_attention,
    )

    cp = 2
    ctx = MeshConfig(cp=cp, dp_shard=4).build()
    B, S = 4, 64
    rng = np.random.default_rng(0)
    seg = np.asarray([0] * 20 + [1] * 12 + [2] * 20 + [3] * 12, np.int32)
    seg = np.broadcast_to(seg, (B, S)).copy()
    pos = np.concatenate([
        np.arange(20), np.arange(12), np.arange(20), np.arange(12)
    ]).astype(np.int32)
    pos = np.broadcast_to(pos, (B, S)).copy()
    q, k, v = _qkv(jax.random.key(3), B=B, S=S)

    sharder = BlockDiagContextParallelSharder(cp_size=cp)
    batch = sharder.shard_batch({
        "input_ids": np.zeros((B, S), np.int32),
        "positions": pos, "segment_ids": seg,
        "q": None,  # not a seq key — untouched
    })
    from automodel_tpu.parallel.cp import document_pack_permutation

    perm = np.stack([document_pack_permutation(row, cp) for row in seg])
    qp = jnp.asarray(np.take_along_axis(np.asarray(q), perm[:, :, None, None], 1))
    kp = jnp.asarray(np.take_along_axis(np.asarray(k), perm[:, :, None, None], 1))
    vp = jnp.asarray(np.take_along_axis(np.asarray(v), perm[:, :, None, None], 1))

    out_local = jax.jit(
        lambda *a: local_cp_attention(
            *a, ctx, causal=True,
        )
    )(qp, kp, vp, jnp.asarray(batch["positions"]), jnp.asarray(batch["segment_ids"]))

    out_ring = jax.jit(
        lambda *a: ring_dot_product_attention(
            *a, ctx, causal=True,
        )
    )(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), jnp.asarray(pos), jnp.asarray(seg))

    # invert the layout: out_local[perm_slot] corresponds to source token
    inv = np.empty_like(perm)
    for b in range(B):
        inv[b, perm[b]] = np.arange(S)
    out_local_nat = np.take_along_axis(
        np.asarray(out_local), inv[:, :, None, None], 1
    )
    np.testing.assert_allclose(
        out_local_nat, np.asarray(out_ring), rtol=2e-4, atol=2e-4
    )


@pytest.mark.slow
def test_decoder_blockdiag_cp_matches_single_device():
    """Full decoder forward: blockdiag layout + local attention == the
    single-device forward on the same packed content (inverted layout)."""
    import dataclasses

    from automodel_tpu.parallel.cp import (
        BlockDiagContextParallelSharder,
        document_pack_permutation,
    )

    cfg = TransformerConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64, num_layers=2,
        num_heads=4, num_kv_heads=2, dtype=jnp.float32, remat_policy="none",
    )
    cfg_bd = dataclasses.replace(cfg, cp_blockdiag=True)
    ctx = MeshConfig(dp_shard=2, tp=2, cp=2).build()
    B, S = 4, 64
    rng = np.random.default_rng(1)
    ids = rng.integers(1, 128, (B, S), dtype=np.int32)
    seg = np.broadcast_to(
        np.asarray([0] * 20 + [1] * 12 + [2] * 20 + [3] * 12, np.int32), (B, S)
    ).copy()
    pos = np.broadcast_to(np.concatenate([
        np.arange(20), np.arange(12), np.arange(20), np.arange(12)
    ]).astype(np.int32), (B, S)).copy()

    params = decoder.init(cfg, jax.random.key(0))
    sharder = BlockDiagContextParallelSharder(cp_size=2)
    batch = sharder.shard_batch(
        {"input_ids": ids, "positions": pos, "segment_ids": seg}
    )
    sh = logical_to_shardings(
        decoder.param_specs(cfg), ctx, shapes=jax.tree.map(lambda p: p.shape, params)
    )
    sharded = jax.device_put(params, sh)

    out_bd = jax.jit(
        lambda p, i, po, sg: decoder.forward(
            p, cfg_bd, i, positions=po, segment_ids=sg, mesh_ctx=ctx
        )
    )(
        sharded, jnp.asarray(batch["input_ids"]),
        jnp.asarray(batch["positions"]), jnp.asarray(batch["segment_ids"]),
    )

    ref = decoder.forward(
        params, cfg, jnp.asarray(ids), positions=jnp.asarray(pos),
        segment_ids=jnp.asarray(seg),
    )
    perm = np.stack([document_pack_permutation(row, 2) for row in seg])
    ref_perm = np.take_along_axis(np.asarray(ref), perm[:, :, None], 1)
    np.testing.assert_allclose(
        np.asarray(out_bd), ref_perm, rtol=3e-4, atol=3e-4
    )


@pytest.mark.recipe
def test_blockdiag_cp_recipe_loss_parity(tmp_path):
    """cp_layout=blockdiag trains on packed data and its per-step losses
    match the balanced-ring run on the SAME data/seed — the reference's
    blockdiag-vs-dense loss-parity contract (blockdiag_cp/ parity tests)."""
    import json

    from automodel_tpu.cli.app import resolve_recipe_class
    from automodel_tpu.config import ConfigNode

    def run(layout, run_dir):
        cfg = ConfigNode({
            "seed": 7,
            "run_dir": str(run_dir),
            "auto_resume": False,
            "recipe": "llm_finetune",
            "model": {"hf_config": {
                "architectures": ["LlamaForCausalLM"],
                "vocab_size": 128, "hidden_size": 32, "intermediate_size": 64,
                "num_hidden_layers": 2, "num_attention_heads": 4,
                "num_key_value_heads": 2,
            }, "dtype": "float32", "remat_policy": "none"},
            "distributed": {"dp_shard": -1, "cp": 2, "cp_layout": layout},
            "dataset": {
                "_target_": "automodel_tpu.datasets.mock.MockDatasetConfig",
                "num_samples": 16, "seq_len": 64, "vocab_size": 128,
                # align = seq_len // cp: capacity-aligned packing, the
                # blockdiag layout's contract (docs never cross a rank)
                "packed": True, "docs_per_sample": 4, "align": 32,
            },
            "dataloader": {"microbatch_size": 8, "grad_acc_steps": 1},
            "optimizer": {"name": "adamw", "lr": 1e-3},
            "lr_scheduler": {"style": "constant", "warmup_steps": 0},
            "step_scheduler": {"max_steps": 2, "ckpt_every_steps": 100},
            "checkpoint": {"enabled": False},
            "loss": {"chunk_size": 64},
        })
        r = resolve_recipe_class(cfg)(cfg)
        r.setup()
        if layout == "blockdiag":
            assert r.model_cfg.cp_blockdiag
            assert type(r.cp_sharder).__name__ == "BlockDiagContextParallelSharder"
        r.run_train_validation_loop()
        return [
            json.loads(l) for l in open(run_dir / "training.jsonl") if l.strip()
        ]

    bd = run("blockdiag", tmp_path / "bd")
    ring = run("balanced", tmp_path / "ring")
    assert len(bd) == len(ring) == 2
    for a, b in zip(bd, ring):
        np.testing.assert_allclose(a["loss"], b["loss"], rtol=1e-4)
