"""Golden-value regression: replay each pinned recipe, compare per-step
metrics against the committed JSONL (reference: ci_tests golden values +
assert_finite_train_metrics.py). Five recipe families are covered: dense,
MoE (ep mesh), LoRA, VLM, dLLM."""

import json
import os

import numpy as np
import pytest

pytestmark = pytest.mark.parity

from automodel_tpu.cli.app import resolve_recipe_class
from tests.golden_config import GOLDEN_RECIPES, golden_path


@pytest.mark.parametrize("name", sorted(GOLDEN_RECIPES))
def test_training_matches_golden(name, tmp_path):
    path = golden_path(name)
    if not os.path.exists(path):
        pytest.skip(f"golden values for '{name}' not generated "
                    "(scripts/generate_golden.py)")
    cfg = GOLDEN_RECIPES[name](str(tmp_path))
    recipe = resolve_recipe_class(cfg)(cfg)
    recipe.setup()
    recipe.run_train_validation_loop()

    got = [json.loads(l) for l in open(tmp_path / "training.jsonl")]
    want = [json.loads(l) for l in open(path)]
    assert [r["step"] for r in got] == [r["step"] for r in want]
    for g, w in zip(got, want):
        for key, tol in (("loss", 1e-4), ("grad_norm", 1e-3), ("lr", 1e-7),
                         ("num_label_tokens", 0.0)):
            np.testing.assert_allclose(
                g[key], w[key], rtol=tol, atol=tol,
                err_msg=f"[{name}] step {g['step']} metric {key}",
            )
        assert np.isfinite(g["loss"]) and np.isfinite(g["grad_norm"])
