"""NemotronH hybrid (mamba + attention + mlp + moe) family tests.

No HF oracle exists in the installed transformers (no nemotron_h module),
so parity is pinned structurally: causality through the mixed stack,
packed-document isolation through the mamba conv+scan, adapter roundtrip
identity, and the full train recipe over an EP mesh (reference:
nemo_automodel/components/models/nemotron_v3/, tests/unit_tests/models/).
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.parity

from automodel_tpu.models.hybrid import nemotron_h as nh

DENSE_HF = {
    "architectures": ["NemotronHForCausalLM"],
    "vocab_size": 128, "hidden_size": 32, "intermediate_size": 64,
    "num_hidden_layers": 4, "hybrid_override_pattern": "M*-M",
    "num_attention_heads": 4, "num_key_value_heads": 2, "attention_head_dim": 8,
    "mamba_num_heads": 4, "mamba_head_dim": 8, "ssm_state_size": 16,
    "n_groups": 2,
}

MOE_HF = dict(
    DENSE_HF,
    architectures=["NemotronHForCausalLM"],
    hybrid_override_pattern="ME*E",
    n_routed_experts=4, num_experts_per_tok=2, moe_intermediate_size=16,
    moe_shared_expert_intermediate_size=16,
)


def test_pattern_parsing_and_registry():
    from automodel_tpu.models.registry import get_model_spec

    spec = get_model_spec(DENSE_HF)
    cfg = spec.config_from_hf(DENSE_HF)
    assert cfg.block_pattern == ("mamba", "attention", "mlp", "mamba")
    cfg2 = nh.from_hf_config(MOE_HF)
    assert cfg2.block_pattern == ("mamba", "moe", "attention", "moe")
    assert cfg2.moe is not None
    assert cfg2.moe.expert_activation == "relu2"
    assert not cfg2.moe.gated_experts  # relu2 experts are non-gated
    assert cfg2.moe.score_func == "sigmoid"


def test_dense_causality_and_grads():
    cfg = nh.from_hf_config(DENSE_HF)
    p = nh.init(cfg, jax.random.key(0))
    ids = jax.random.randint(jax.random.key(1), (2, 16), 0, 128)
    out = nh.forward(p, cfg, ids)
    assert bool(jnp.isfinite(out).all())
    # causality through every mixer kind: flipping the last token must not
    # change earlier logits
    ids2 = ids.at[:, -1].set((ids[:, -1] + 1) % 128)
    out2 = nh.forward(p, cfg, ids2)
    np.testing.assert_allclose(
        np.asarray(out[:, :-1]), np.asarray(out2[:, :-1]), atol=1e-5
    )

    def loss(pp):
        return jnp.mean(
            jax.nn.logsumexp(nh.forward(pp, cfg, ids), axis=-1)
        )

    grads = jax.grad(loss)(p)
    assert all(bool(jnp.isfinite(g).all()) for g in jax.tree.leaves(grads))


def test_packed_segment_isolation():
    """Concatenating two docs with segment ids must reproduce each doc run
    alone — the conv taps and the SSD state reset at doc boundaries, and
    attention masks across segments."""
    cfg = nh.from_hf_config(DENSE_HF)
    p = nh.init(cfg, jax.random.key(0))
    a = jax.random.randint(jax.random.key(1), (1, 8), 0, 128)
    b = jax.random.randint(jax.random.key(2), (1, 8), 0, 128)
    packed = jnp.concatenate([a, b], axis=1)
    seg = jnp.concatenate(
        [jnp.zeros((1, 8), jnp.int32), jnp.ones((1, 8), jnp.int32)], axis=1
    )
    pos = jnp.concatenate([jnp.arange(8), jnp.arange(8)])[None]
    out_packed = nh.forward(p, cfg, packed, segment_ids=seg, positions=pos)
    out_a = nh.forward(p, cfg, a)
    out_b = nh.forward(p, cfg, b)
    np.testing.assert_allclose(
        np.asarray(out_packed[:, :8]), np.asarray(out_a), atol=2e-4
    )
    np.testing.assert_allclose(
        np.asarray(out_packed[:, 8:]), np.asarray(out_b), atol=2e-4
    )


def test_adapter_roundtrip():
    cfg = nh.from_hf_config(dict(MOE_HF, hybrid_override_pattern="M*-E"))
    p = nh.init(cfg, jax.random.key(0))
    ad = nh.NemotronHAdapter(cfg)
    sd = dict(ad.to_hf(p))
    # HF-style key layout present
    assert "backbone.layers.0.mixer.A_log" in sd
    assert "backbone.layers.1.mixer.q_proj.weight" in sd
    assert "backbone.layers.2.mixer.up_proj.weight" in sd
    assert "backbone.layers.3.mixer.experts.0.up_proj.weight" in sd
    p2 = ad.from_hf(lambda k: sd[k])
    ids = jax.random.randint(jax.random.key(1), (2, 16), 0, 128)
    o1, _ = nh.forward(p, cfg, ids)
    o2, _ = nh.forward(p2, cfg, ids)
    np.testing.assert_allclose(np.asarray(o1), np.asarray(o2), atol=1e-5)


def test_nemotron_h_recipe_ep_mesh(tmp_path):
    from automodel_tpu.cli.app import resolve_recipe_class
    from tests.unit.test_recipe import _smoke_cfg

    cfg = _smoke_cfg(tmp_path)
    cfg.set("model.hf_config", MOE_HF)
    cfg.set("distributed", {"dp_shard": -1, "ep": 2})
    cfg.set("checkpoint.enabled", False)
    cfg.set("step_scheduler.max_steps", 3)
    r = resolve_recipe_class(cfg)(cfg)
    r.setup()
    assert r.is_moe
    r.run_train_validation_loop()
    recs = [json.loads(l) for l in open(tmp_path / "training.jsonl") if l.strip()]
    assert len(recs) == 3
    assert all(np.isfinite(x["loss"]) for x in recs)
    assert "moe_load_imbalance" in recs[-1]


def test_qwen3_next_adapter_roundtrip():
    """to_hf is the exact inverse of from_hf (VERDICT r3 #9: export
    previously raised)."""
    from automodel_tpu.models.hybrid import qwen3_next as qn

    hf = {
        "vocab_size": 128, "hidden_size": 32, "intermediate_size": 64,
        "num_hidden_layers": 4, "num_attention_heads": 4,
        "num_key_value_heads": 2, "head_dim": 8,
        "layer_types": [
            "linear_attention", "full_attention",
            "linear_attention", "full_attention",
        ],
        "linear_num_value_heads": 4, "linear_num_key_heads": 2,
        "linear_key_head_dim": 8, "linear_value_head_dim": 8,
        "num_experts": 4, "num_experts_per_tok": 2,
        "moe_intermediate_size": 16, "shared_expert_intermediate_size": 16,
        "norm_topk_prob": True, "rope_theta": 10000.0,
    }
    cfg = qn.from_hf_config(hf, remat_policy="none")
    p = qn.init(cfg, jax.random.key(0))
    ad = qn.Qwen3NextAdapter(cfg)
    sd = dict(ad.to_hf(p))
    assert "model.layers.0.linear_attn.conv1d.weight" in sd
    assert sd["model.layers.0.linear_attn.conv1d.weight"].ndim == 3
    assert "model.layers.1.self_attn.q_norm.weight" in sd
    assert "model.layers.2.mlp.experts.3.down_proj.weight" in sd
    assert "model.layers.3.mlp.shared_expert_gate.weight" in sd
    p2 = ad.from_hf(lambda k: np.asarray(sd[k]))
    ids = jax.random.randint(jax.random.key(1), (2, 16), 0, 128)
    o1, _ = qn.forward(p, cfg, ids)
    o2, _ = qn.forward(p2, cfg, ids)
    np.testing.assert_allclose(np.asarray(o1), np.asarray(o2), atol=1e-5)


def test_chunked_ssd_matches_scan():
    """Chunked SSD block form == sequential scan oracle (incl. packed-doc
    resets and a non-chunk-divisible length)."""
    from automodel_tpu.models.hybrid.mamba2 import (
        selective_scan,
        selective_scan_chunked,
    )

    rng = np.random.default_rng(0)
    Bz, S, H, P, N = 2, 200, 4, 8, 16
    x = jnp.asarray(rng.normal(size=(Bz, S, H, P)), jnp.float32)
    dt = jnp.asarray(rng.uniform(0.01, 1.0, size=(Bz, S, H)), jnp.float32)
    A = -jnp.asarray(rng.uniform(0.5, 2.0, size=(H,)), jnp.float32)
    B = jnp.asarray(rng.normal(size=(Bz, S, H, N)), jnp.float32)
    C = jnp.asarray(rng.normal(size=(Bz, S, H, N)), jnp.float32)
    D = jnp.asarray(rng.normal(size=(H,)), jnp.float32)
    reset = jnp.zeros((Bz, S), bool).at[:, 77].set(True).at[0, 150].set(True)

    y1 = selective_scan(x, dt, A, B, C, D, reset)
    y2 = selective_scan_chunked(x, dt, A, B, C, D, reset, chunk=64)
    np.testing.assert_allclose(np.asarray(y1), np.asarray(y2), rtol=2e-4, atol=3e-4)

    # gradients flow through the chunked form identically — dt grads pass
    # through the masked pairwise exp (the 0·inf NaN trap across resets)
    g1 = jax.grad(
        lambda x, dt: jnp.sum(selective_scan(x, dt, A, B, C, D, reset) ** 2),
        argnums=(0, 1),
    )(x, dt)
    g2 = jax.grad(
        lambda x, dt: jnp.sum(
            selective_scan_chunked(x, dt, A, B, C, D, reset, chunk=64) ** 2
        ),
        argnums=(0, 1),
    )(x, dt)
    for a, b, n in zip(g1, g2, ("x", "dt")):
        assert np.isfinite(np.asarray(a)).all(), f"d{n} not finite"
        # fp32 reduction-order noise on O(1e3) grad values needs looser rtol
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=8e-3, atol=2e-3, err_msg=f"d{n}"
        )


def test_chunked_gdn_matches_scan():
    """Chunked (WY) gated delta rule == sequential oracle, fwd + grad."""
    from automodel_tpu.models.hybrid.qwen3_next import (
        _l2norm,
        gated_delta_rule,
        gated_delta_rule_chunked,
    )

    rng = np.random.default_rng(1)
    B, S, H, dk, dv = 2, 150, 3, 16, 32
    q = _l2norm(jnp.asarray(rng.normal(size=(B, S, H, dk)), jnp.float32)) * dk ** -0.5
    k = _l2norm(jnp.asarray(rng.normal(size=(B, S, H, dk)), jnp.float32))
    v = jnp.asarray(rng.normal(size=(B, S, H, dv)), jnp.float32)
    g = -jnp.asarray(rng.uniform(0.01, 2.0, size=(B, S, H)), jnp.float32)
    beta = jnp.asarray(rng.uniform(0.1, 0.9, size=(B, S, H)), jnp.float32)

    y1 = gated_delta_rule(q, k, v, g, beta)
    y2 = gated_delta_rule_chunked(q, k, v, g, beta, chunk=32)
    np.testing.assert_allclose(np.asarray(y1), np.asarray(y2), rtol=3e-4, atol=3e-4)

    # g-grads pass through the masked pairwise exp (the 0·inf NaN trap when
    # per-chunk |g| sums exceed the fp32 exp range); use strong decay + a
    # large chunk so unmasked diffs would overflow without mask-before-exp
    g_strong = -jnp.asarray(rng.uniform(2.0, 6.0, size=(B, S, H)), jnp.float32)
    g1 = jax.grad(
        lambda v, gg: jnp.sum(gated_delta_rule(q, k, v, gg, beta) ** 2),
        argnums=(0, 1),
    )(v, g_strong)
    g2 = jax.grad(
        lambda v, gg: jnp.sum(
            gated_delta_rule_chunked(q, k, v, gg, beta, chunk=64) ** 2
        ),
        argnums=(0, 1),
    )(v, g_strong)
    for a, b, n in zip(g1, g2, ("v", "g")):
        assert np.isfinite(np.asarray(a)).all(), f"d{n} not finite"
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-3, atol=2e-3, err_msg=f"d{n}"
        )
