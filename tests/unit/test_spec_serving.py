"""Speculative decoding in the continuous batcher: parity + lifetimes.

The acceptance contract of per-slot draft-then-verify:

- the shared acceptance rule (speculative/acceptance.py) is property-
  tested: greedy acceptance IS the longest matching prefix, and sampled
  (one-hot) acceptance preserves the target distribution on a toy vocab;
- committed tokens on ragged greedy streams (staggered arrivals, forced
  preemption, prefix-cache hits enabled) are token-for-token identical to
  the speculation-DISABLED engine, with the step compiling ONCE — for the
  ngram source and for EAGLE/DFlash drafter adapters (whose random-weight
  drafts are mostly rejected: verification makes quality a throughput
  knob, never a correctness one);
- provisional draft pages never leak: deadline eviction, preempt-and-
  requeue, and prefix-cache donation all free/skip in-flight draft pages.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from automodel_tpu.models.llm import decoder
from automodel_tpu.models.llm.decoder import TransformerConfig, head_kernel
from automodel_tpu.serving import (
    PrefixCacheConfig,
    Request,
    ServingConfig,
    ServingEngine,
    SpeculativeConfig,
)
from automodel_tpu.speculative.acceptance import (
    greedy_accept_length,
    onehot_speculative_verify,
)

CFG = TransformerConfig(
    vocab_size=64, hidden_size=32, intermediate_size=48, num_layers=2,
    num_heads=4, num_kv_heads=2, qk_norm=True, dtype=jnp.float32,
    remat_policy="none",
)


def _params():
    return decoder.init(CFG, jax.random.key(0))


def _ragged(seed0, lens, vocab=64):
    return [
        [int(t) for t in np.random.default_rng(seed0 + i).integers(1, vocab, (l,))]
        for i, l in enumerate(lens)
    ]


def _serve(params, geo, reqs, spec=None, prefix=None, draft_source=None):
    engine = ServingEngine(
        params, CFG,
        ServingConfig(**geo, speculative=spec, prefix_cache=prefix),
        draft_source=draft_source,
    )
    res = engine.serve_batch([
        Request(
            prompt=list(r.prompt), max_new_tokens=r.max_new_tokens,
            arrival=r.arrival, temperature=r.temperature, seed=r.seed,
            eos_token_id=r.eos_token_id, deadline=r.deadline,
        )
        for r in reqs
    ])
    return res, engine


SPEC = SpeculativeConfig(enabled=True, draft_len=4)


# -- acceptance rule properties (satellite: one shared implementation) ------
def test_greedy_acceptance_is_longest_matching_prefix():
    """Fuzz vs the obvious python loop, incl. validity masking."""
    rng = np.random.default_rng(0)
    for _ in range(50):
        K = int(rng.integers(1, 7))
        draft = rng.integers(0, 4, K)
        target = rng.integers(0, 4, K)
        k_valid = int(rng.integers(0, K + 1))
        valid = np.arange(K) < k_valid
        expect = 0
        for j in range(k_valid):
            if draft[j] != target[j]:
                break
            expect += 1
        got = int(greedy_accept_length(
            jnp.asarray(draft), jnp.asarray(target), jnp.asarray(valid)
        ))
        assert got == expect, (draft, target, k_valid, got, expect)


def test_greedy_acceptance_batched_axis():
    d = jnp.asarray([[1, 2, 3], [1, 9, 3]])
    t = jnp.asarray([[1, 2, 9], [1, 2, 3]])
    assert list(greedy_accept_length(d, t)) == [2, 1]


def test_sampled_acceptance_preserves_target_distribution():
    """One-hot speculative verification on a toy vocab: over many keys the
    FIRST committed token's empirical law must equal softmax(logits row 0)
    regardless of what the (deterministic) draft proposed — the Leviathan
    guarantee that speculation never changes the distribution."""
    V, K = 5, 3
    logits = jnp.asarray(np.random.default_rng(1).normal(size=(K + 1, V)), jnp.float32)
    target = np.asarray(jax.nn.softmax(logits[0]))
    draft = jnp.asarray([2, 0, 4])
    valid = jnp.ones(K, bool)

    def one(seed):
        keys = jax.vmap(
            lambda j: jax.random.fold_in(jax.random.key(seed), j)
        )(jnp.arange(K + 1))
        a, toks = onehot_speculative_verify(draft, logits, keys, valid)
        # first committed token: the draft if accepted, else the resample
        return jnp.where(a >= 1, draft[0], toks[jnp.clip(a, 0, K)])

    n = 6000
    first = np.asarray(jax.vmap(one)(jnp.arange(n)))
    emp = np.bincount(first, minlength=V) / n
    assert np.abs(emp - target).max() < 0.03, (emp, target)


def test_sampled_acceptance_full_accept_bonus_is_plain_sample():
    """With every draft accepted, the bonus token must be the PLAIN
    categorical sample of the bonus row under its own key — so an empty
    block (valid all-False) degenerates to ordinary sampling exactly."""
    V, K = 7, 2
    logits = jnp.asarray(np.random.default_rng(2).normal(size=(K + 1, V)), jnp.float32)
    keys = jax.vmap(
        lambda j: jax.random.fold_in(jax.random.key(123), j)
    )(jnp.arange(K + 1))
    a, toks = onehot_speculative_verify(
        jnp.zeros(K, jnp.int32), logits, keys, jnp.zeros(K, bool)
    )
    assert int(a) == 0
    assert int(toks[0]) == int(jax.random.categorical(keys[0], logits[0]))


# -- greedy parity on ragged streams ---------------------------------------
def test_spec_parity_ragged_stream_compiles_once():
    """Staggered arrivals + chunked prefill interleaved with drafted decode
    blocks: committed tokens equal the speculation-disabled engine exactly,
    ONE compiled signature, and the counters add up."""
    params = _params()
    geo = dict(page_size=4, num_pages=24, max_slots=3, pages_per_slot=6,
               token_budget=16, prefill_chunk=4)
    prompts = _ragged(0, [5, 9, 3, 7, 11])
    reqs = [Request(prompt=p, max_new_tokens=8, arrival=a)
            for p, a in zip(prompts, [0, 0, 2, 3, 5])]
    plain, _ = _serve(params, geo, reqs)
    spec, eng = _serve(params, geo, reqs, spec=SPEC)
    assert spec["outputs"] == plain["outputs"]
    assert spec["stats"]["compiled_signatures"] == 1
    assert eng.step_cache_size() == 1
    s = spec["stats"]
    assert s["drafted_tokens"] >= 1 and s["spec_steps"] >= 1
    assert s["drafted_tokens"] == s["accepted_tokens"] + s["rolled_back_tokens"]
    assert s["mean_accepted_len"] >= 1.0


def test_spec_parity_under_forced_preemption():
    """A pool too small for the admitted set forces recompute-style
    preemption while slots are mid-speculation; greedy outputs stay exact
    and a preempted request re-admits cleanly (the provisional pages were
    rolled back before its pages were freed)."""
    params = _params()
    geo = dict(page_size=2, num_pages=8, max_slots=3, pages_per_slot=6,
               token_budget=8, prefill_chunk=3)
    reqs = [Request(prompt=p, max_new_tokens=5)
            for p in _ragged(20, [4, 4, 4])]
    plain, _ = _serve(params, geo, reqs)
    spec, _ = _serve(params, geo, reqs, spec=SpeculativeConfig(
        enabled=True, draft_len=3,
    ))
    assert spec["outputs"] == plain["outputs"]
    assert spec["stats"]["preemptions"] >= 1
    assert spec["stats"]["compiled_signatures"] == 1


def test_spec_parity_with_prefix_cache_hits():
    """Agent-loop stream with the radix cache on: prefix hits, COW, draft
    blocks, and donation compose — token-exact vs the plain cold engine,
    and every donated page holds committed (never provisional) content or
    the hits themselves would corrupt later requests."""
    params = _params()
    rng = np.random.default_rng(7)
    system = [int(t) for t in rng.integers(1, 64, (10,))]
    reqs = []
    for a in range(2):
        hist = list(system)
        for r in range(2):
            hist = hist + [int(t) for t in rng.integers(1, 64, (3,))]
            reqs.append(Request(
                prompt=list(hist), max_new_tokens=6, arrival=r * 8 + a,
            ))
    geo = dict(page_size=4, num_pages=48, max_slots=3, pages_per_slot=12,
               token_budget=12, prefill_chunk=4)
    plain, _ = _serve(params, geo, reqs)
    both, _ = _serve(params, geo, reqs, spec=SPEC,
                     prefix=PrefixCacheConfig(enabled=True))
    assert both["outputs"] == plain["outputs"]
    assert both["stats"]["prefix_hits"] >= 1
    assert both["stats"]["drafted_tokens"] >= 1
    assert both["stats"]["compiled_signatures"] == 1


def test_spec_eos_stops_mid_block():
    """An EOS committed from inside an accepted draft block (or its bonus)
    must stop the request exactly where the plain engine stops it."""
    params = _params()
    (prompt,) = _ragged(30, [5])
    geo = dict(page_size=4, num_pages=16, max_slots=2, pages_per_slot=4,
               token_budget=8)
    ref, _ = _serve(params, geo, [Request(prompt=prompt, max_new_tokens=8)])
    eos = ref["outputs"][0][2]  # third greedy token becomes EOS
    plain, _ = _serve(params, geo, [
        Request(prompt=prompt, max_new_tokens=8, eos_token_id=eos)
    ])
    spec, _ = _serve(params, geo, [
        Request(prompt=prompt, max_new_tokens=8, eos_token_id=eos)
    ], spec=SPEC)
    assert spec["outputs"] == plain["outputs"]
    assert spec["requests"][0].finish_reason == "eos"
    assert spec["requests"][0].generated[-1] == eos


def test_eos_inside_accepted_block_keeps_fed_invariant():
    """An EOS cut INSIDE the accepted prefix discards the block's tail:
    `fed` must never exceed len(known) and the acceptance counters must
    count only committed drafts (scheduler-level, engine-free)."""
    from automodel_tpu.speculative.serve_draft import NgramDraftSource

    from automodel_tpu.serving import Scheduler

    spec = SpeculativeConfig(enabled=True, draft_len=4)
    sched = Scheduler(
        num_pages=16, page_size=2, max_slots=1, pages_per_slot=8,
        token_budget=12, spec=spec, draft_source=NgramDraftSource(spec),
    )
    req = Request(prompt=[3, 4, 3, 4, 3, 4, 3], max_new_tokens=8,
                  eos_token_id=9)
    sched.submit(req)
    plan = sched.schedule(0)
    sched.update(plan, np.full((1, 5), 4, np.int32), 0,
                 accept=np.zeros(1, np.int32))
    plan = sched.schedule(1)
    k = int(plan.spec_len[0])
    assert k >= 2
    drafted0, accepted0 = sched.n_drafted, sched.n_accepted
    # verifier "accepts everything" but the FIRST committed token is EOS
    block = np.full((1, 5), 9, np.int32)
    sched.update(plan, block, 1, accept=np.full(1, k, np.int32))
    assert req.done and req.finish_reason == "eos"
    assert req.fed <= len(req.known)
    assert sched.n_drafted - drafted0 == k
    assert sched.n_accepted - accepted0 <= 1  # only the COMMITTED draft
    assert sched.alloc.num_free == 16  # released: nothing leaks


# -- provisional-page lifetimes (satellite: eviction/preempt/donation) ------
def test_deadline_eviction_frees_in_flight_draft_pages():
    """A request evicted by its deadline while actively speculating must
    return EVERY page to the pool — provisional tails included."""
    params = _params()
    geo = dict(page_size=2, num_pages=8, max_slots=2, pages_per_slot=8,
               token_budget=8, prefill_chunk=4)
    hog, blocked = _ragged(90, [8, 6])
    res, eng = _serve(params, geo, [
        Request(prompt=hog, max_new_tokens=8, deadline=6),
        Request(prompt=blocked, max_new_tokens=3, arrival=1),
    ], spec=SpeculativeConfig(enabled=True, draft_len=3))
    assert res["stats"]["timed_out"] == 1
    plain, _ = _serve(params, geo, [
        Request(prompt=hog, max_new_tokens=8, deadline=6),
        Request(prompt=blocked, max_new_tokens=3, arrival=1),
    ])
    # the survivor keeps exact parity and the pool drains completely
    assert res["outputs"][1] == plain["outputs"][1]


def test_preempt_mid_speculation_rolls_back_then_requeues():
    """Scheduler-level: after a drafted verify step, the slot's table has
    NO provisional tail (update truncated it), so preempting the request
    frees exactly its committed pages and it re-admits cleanly."""
    from automodel_tpu.speculative.serve_draft import NgramDraftSource

    from automodel_tpu.serving import Scheduler, pages_for

    spec = SpeculativeConfig(enabled=True, draft_len=4)
    sched = Scheduler(
        num_pages=16, page_size=2, max_slots=2, pages_per_slot=8,
        token_budget=12, spec=spec, draft_source=NgramDraftSource(spec),
    )
    # repetitive prompt → the ngram source always has a proposal
    req = Request(prompt=[3, 4, 3, 4, 3, 4, 3], max_new_tokens=8)
    sched.submit(req)
    plan = sched.schedule(0)          # prefill (commits "4": pattern holds)
    sched.update(plan, np.full((2, 5), 4, np.int32), 0,
                 accept=np.zeros(2, np.int32))
    plan = sched.schedule(1)          # decode + drafts
    (slot, c, samples) = plan.scheduled[0]
    k = int(plan.spec_len[slot])
    assert samples and c == 1 and k >= 1
    held_before = len(sched.alloc.table(slot))
    # model "rejected everything": accept 0 of k drafts
    block = np.tile(np.arange(5, dtype=np.int32), (2, 1))
    sched.update(plan, block, 1, accept=np.zeros(2, np.int32))
    # rollback truncated the provisional tail to exactly the committed KV
    assert len(sched.alloc.table(slot)) == pages_for(req.fed, 2)
    assert len(sched.alloc.table(slot)) <= held_before
    # preempt-and-requeue sees only committed pages; everything frees
    assert sched._preempt_youngest(set())
    assert sched.alloc.num_free == 16
    assert req.fed == 0 and req in sched.waiting


def test_donation_never_covers_provisional_pages():
    """Prefix-cache donation is driven by the rolled-back `fed`, so a page
    the tree serves to a later request can only hold committed KV: a
    full-page-aligned request that speculated heavily donates pages whose
    token keys are exactly its committed stream."""
    params = _params()
    geo = dict(page_size=4, num_pages=32, max_slots=2, pages_per_slot=8,
               token_budget=12, prefill_chunk=4)
    (p,) = _ragged(40, [8])
    spec_cfg = SpeculativeConfig(enabled=True, draft_len=4)
    # same prompt twice: the second admits over donated pages
    reqs = [
        Request(prompt=p, max_new_tokens=6),
        Request(prompt=p, max_new_tokens=6, arrival=6),
    ]
    plain, _ = _serve(params, geo, reqs)
    both, _ = _serve(params, geo, reqs, spec=spec_cfg,
                     prefix=PrefixCacheConfig(enabled=True))
    assert both["outputs"] == plain["outputs"]
    assert both["outputs"][0] == both["outputs"][1]
    assert both["stats"]["prefix_hits"] >= 1


def test_draft_blocks_never_starve_later_decode_slots():
    """A tight token budget with long draft blocks: every decode-class
    slot must still get its one guaranteed row per step — an earlier
    slot's speculation shrinks instead (stable decode order would starve
    the same slot every step otherwise)."""
    from automodel_tpu.speculative.serve_draft import NgramDraftSource

    from automodel_tpu.serving import Scheduler

    spec = SpeculativeConfig(enabled=True, draft_len=6)
    sched = Scheduler(
        num_pages=48, page_size=2, max_slots=3, pages_per_slot=16,
        token_budget=8, spec=spec, draft_source=NgramDraftSource(spec),
    )
    for _ in range(3):
        # repetitive prompts → the ngram source always proposes a long block
        sched.submit(Request(prompt=[3, 4, 3, 4, 3, 4, 3], max_new_tokens=16))
    step = 0
    while any(
        len(r.known) - r.fed > 1 for r in sched.running.values()
    ) or not sched.running:
        plan = sched.schedule(step)
        assert plan is not None
        sched.update(plan, np.full((3, 7), 4, np.int32), step,
                     accept=np.zeros(3, np.int32))
        step += 1
        assert step < 20
    # all three are decode-class now: every one gets a row this step
    plan = sched.schedule(step)
    slots = [s for s, _, _ in plan.scheduled]
    assert sorted(slots) == sorted(sched.running.keys())
    assert all(c == 1 for _, c, _ in plan.scheduled)
    # and the early slots actually drafted into the leftover budget
    assert int(plan.spec_len.sum()) >= 1
    assert sum(c for _, c, _ in plan.scheduled) + int(plan.spec_len.sum()) <= 8


# -- sampled mode -----------------------------------------------------------
def test_sampled_spec_batching_invariant_and_deterministic():
    """Sampled acceptance derives every accept/resample decision from
    (request seed, absolute position) and draft sources are deterministic
    functions of the known tokens — so a sampled request commits the SAME
    tokens regardless of engine geometry or co-resident traffic."""
    params = _params()
    spec = SpeculativeConfig(enabled=True, draft_len=3, acceptance="sampled")

    def run(geo, extra):
        reqs = [Request(prompt=[5, 9, 2, 7, 1], max_new_tokens=6,
                        temperature=0.8, seed=7)]
        reqs += [Request(prompt=p, max_new_tokens=4, temperature=0.5,
                         seed=1 + i) for i, p in enumerate(extra)]
        res, _ = _serve(params, geo, reqs, spec=spec)
        return res["outputs"][0]

    a = run(dict(page_size=4, num_pages=32, max_slots=2, pages_per_slot=8,
                 token_budget=8), [])
    b = run(dict(page_size=2, num_pages=40, max_slots=3, pages_per_slot=16,
                 token_budget=12, prefill_chunk=2), _ragged(70, [6, 3]))
    assert a == b
    assert all(0 <= t < 64 for t in a)


def test_greedy_acceptance_mode_never_drafts_sampled_slots():
    """acceptance='greedy' (default) must not speculate on temperature>0
    requests — greedy acceptance would skew their distribution — while
    still sampling them exactly like the plain engine."""
    params = _params()
    geo = dict(page_size=4, num_pages=32, max_slots=2, pages_per_slot=8,
               token_budget=8)
    reqs = [Request(prompt=[5, 9, 2, 7, 1], max_new_tokens=6,
                    temperature=0.8, seed=7)]
    plain, _ = _serve(params, geo, reqs)
    spec, _ = _serve(params, geo, reqs, spec=SPEC)
    assert spec["outputs"] == plain["outputs"]
    assert spec["stats"]["drafted_tokens"] == 0


# -- drafter adapters (EAGLE / DFlash reuse of speculative/) ----------------
@pytest.mark.slow
def test_eagle_adapter_parity_and_feedback():
    """EAGLE chain-draft adapter: the engine feeds frontier hiddens back,
    the drafter chains K argmax steps, and (random weights or not) the
    committed stream equals the plain engine's."""
    from automodel_tpu.serving import EagleDraftSource
    from automodel_tpu.speculative.eagle1 import Eagle1Config, init_drafter

    params = _params()
    ecfg = Eagle1Config(vocab_size=64, hidden_size=32, intermediate_size=48,
                        num_heads=4, num_kv_heads=2, num_layers=1)
    source = EagleDraftSource(
        init_drafter(ecfg, jax.random.key(1)), ecfg,
        head_kernel(params, CFG), draft_len=3, window=8,
    )
    geo = dict(page_size=4, num_pages=32, max_slots=2, pages_per_slot=8,
               token_budget=10, prefill_chunk=4)
    reqs = [Request(prompt=p, max_new_tokens=6, arrival=a)
            for p, a in zip(_ragged(50, [5, 8]), (0, 1))]
    plain, _ = _serve(params, geo, reqs)
    spec, _ = _serve(
        params, geo, reqs, draft_source=source,
        spec=SpeculativeConfig(enabled=True, draft_len=3, draft_source="eagle"),
    )
    assert spec["outputs"] == plain["outputs"]
    assert spec["stats"]["drafted_tokens"] >= 1
    assert spec["stats"]["compiled_signatures"] == 1


@pytest.mark.slow
def test_dflash_adapter_parity_and_feedback():
    """DFlash block-draft adapter: per-row hiddens accumulate into the
    drafter's context, one forward drafts the block — parity regardless of
    draft quality, one compiled step."""
    from automodel_tpu.serving import DFlashDraftSource
    from automodel_tpu.speculative.dflash import DFlashConfig, init_drafter

    params = _params()
    dcfg = DFlashConfig(
        vocab_size=64, hidden_size=32, intermediate_size=48, num_heads=4,
        num_kv_heads=2, num_layers=1, block_size=4, target_hidden_size=32,
        num_target_layers_used=1,
    )
    source = DFlashDraftSource(
        init_drafter(dcfg, jax.random.key(2)), dcfg,
        params["embed"]["embedding"], head_kernel(params, CFG),
        max_context=32,
    )
    geo = dict(page_size=4, num_pages=32, max_slots=2, pages_per_slot=8,
               token_budget=10, prefill_chunk=4)
    reqs = [Request(prompt=p, max_new_tokens=6, arrival=a)
            for p, a in zip(_ragged(60, [5, 8]), (0, 1))]
    plain, _ = _serve(params, geo, reqs)
    spec, _ = _serve(
        params, geo, reqs, draft_source=source,
        spec=SpeculativeConfig(enabled=True, draft_len=3, draft_source="dflash"),
    )
    assert spec["outputs"] == plain["outputs"]
    assert spec["stats"]["drafted_tokens"] >= 1
    assert spec["stats"]["compiled_signatures"] == 1


@pytest.mark.slow
def test_mla_spec_parity():
    """Absorbed-MLA paged layout under speculation (the verify block rides
    the latent-cache attention path)."""
    mla = dataclasses.replace(
        CFG, attention_type="mla", mla_kv_lora_rank=16, mla_q_lora_rank=12,
        mla_qk_nope_head_dim=8, mla_qk_rope_head_dim=8, mla_v_head_dim=8,
    )
    params = decoder.init(mla, jax.random.key(0))

    def serve(spec):
        engine = ServingEngine(params, mla, ServingConfig(
            page_size=4, num_pages=20, max_slots=2, pages_per_slot=5,
            token_budget=10, prefill_chunk=3, speculative=spec,
        ))
        return engine.serve_batch([
            Request(prompt=list(p), max_new_tokens=5, arrival=a)
            for p, a in zip(_ragged(10, [6, 9]), (0, 1))
        ])

    plain = serve(None)
    spec = serve(SpeculativeConfig(enabled=True, draft_len=3))
    assert spec["outputs"] == plain["outputs"]
    assert spec["stats"]["compiled_signatures"] == 1


def test_config_validation():
    with pytest.raises(ValueError):
        SpeculativeConfig(enabled=True, draft_source="nope")
    with pytest.raises(ValueError):
        SpeculativeConfig(enabled=True, acceptance="mode7")
    with pytest.raises(ValueError):
        SpeculativeConfig(enabled=True, draft_len=0)
    with pytest.raises(ValueError):
        SpeculativeConfig(enabled=True, ngram_min=0)
    with pytest.raises(AssertionError):
        ServingConfig(token_budget=4, speculative=SpeculativeConfig(
            enabled=True, draft_len=4,
        ))
    # eagle/dflash need drafter params — config alone must refuse loudly
    from automodel_tpu.speculative.serve_draft import build_draft_source

    with pytest.raises(ValueError):
        build_draft_source(
            SpeculativeConfig(enabled=True, draft_source="eagle"),
            max_context=64,
        )
