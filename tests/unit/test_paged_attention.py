"""Ragged paged attention: XLA reference vs dense oracle, Pallas parity.

The XLA gather-based reference is checked against `ops/attention.py`'s
dense einsum attention on a contiguous cache scattered into randomly-
permuted pages; the Pallas kernels (interpret mode on CPU) are then checked
against the reference — the same two-hop oracle chain as flash attention.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from automodel_tpu.ops.attention import xla_attention
from automodel_tpu.ops.paged_attention import (
    ragged_paged_attention,
    ragged_paged_attention_xla,
    ragged_paged_mla_attention_xla,
)


def _paged_setup(seed=0, T=6, Hkv=2, G=2, D=16, Dv=16, ps=4, P=5, N=12):
    """Scatter a contiguous (T_ctx, Hkv, D) cache into shuffled pool pages;
    token t sees positions 0..pos[t] of the context."""
    rng = np.random.default_rng(seed)
    Hq = Hkv * G
    ctx = P * ps
    q = jnp.asarray(rng.normal(size=(T, Hq, D)), jnp.float32)
    keys = jnp.asarray(rng.normal(size=(ctx, Hkv, D)), jnp.float32)
    values = jnp.asarray(rng.normal(size=(ctx, Hkv, Dv)), jnp.float32)
    pages = rng.permutation(N)[:P]              # the pool pages backing ctx
    k_pages = jnp.asarray(rng.normal(size=(N + 1, ps, Hkv, D)), jnp.float32)
    v_pages = jnp.asarray(rng.normal(size=(N + 1, ps, Hkv, Dv)), jnp.float32)
    k_pages = k_pages.at[pages].set(keys.reshape(P, ps, Hkv, D))
    v_pages = v_pages.at[pages].set(values.reshape(P, ps, Hkv, Dv))
    pt = jnp.broadcast_to(jnp.asarray(pages, jnp.int32), (T, P))
    pos = jnp.asarray(rng.integers(0, ctx, (T,)), jnp.int32)
    return q, keys, values, k_pages, v_pages, pt, pos


def _dense_oracle(q, keys, values, pos, window=None, soft_cap=None, sinks=None):
    """Per-token dense attention over positions <= pos[t]."""
    T = q.shape[0]
    ctx = keys.shape[0]
    kv_idx = jnp.arange(ctx)
    mask = kv_idx[None, :] <= pos[:, None]
    if window is not None:
        dist = pos[:, None] - kv_idx[None, :]
        mask = jnp.logical_and(mask, (window == 0) | (dist < window))
    out = xla_attention(
        q[:, None], jnp.broadcast_to(keys[None], (T, *keys.shape)),
        jnp.broadcast_to(values[None], (T, *values.shape)),
        mask=mask[:, None, :], scale=q.shape[-1] ** -0.5,
        logits_soft_cap=soft_cap, sinks=sinks,
    )
    return out[:, 0]


def test_xla_reference_matches_dense_oracle():
    q, keys, values, kp, vp, pt, pos = _paged_setup()
    got = ragged_paged_attention_xla(q, kp, vp, pt, pos, scale=q.shape[-1] ** -0.5)
    want = _dense_oracle(q, keys, values, pos)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)


def test_xla_reference_window_softcap_sinks():
    q, keys, values, kp, vp, pt, pos = _paged_setup(seed=1)
    sinks = jnp.asarray([0.3, -0.2, 0.1, 0.5], jnp.float32)
    got = ragged_paged_attention_xla(
        q, kp, vp, pt, pos, scale=q.shape[-1] ** -0.5,
        window=jnp.int32(5), soft_cap=10.0, sinks=sinks,
    )
    want = _dense_oracle(q, keys, values, pos, window=jnp.int32(5),
                         soft_cap=10.0, sinks=sinks)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)
    # window == 0 means global (the layer-scan convention)
    got0 = ragged_paged_attention_xla(
        q, kp, vp, pt, pos, scale=q.shape[-1] ** -0.5, window=jnp.int32(0),
    )
    want0 = _dense_oracle(q, keys, values, pos)
    np.testing.assert_allclose(np.asarray(got0), np.asarray(want0), atol=1e-5)


def test_pad_rows_zero():
    q, keys, values, kp, vp, pt, pos = _paged_setup(seed=2)
    pos = pos.at[2].set(-1).at[5].set(-1)
    got = ragged_paged_attention_xla(q, kp, vp, pt, pos, scale=0.25)
    assert np.asarray(got)[2].max() == 0.0 and np.asarray(got)[5].max() == 0.0
    # sinks must not leak mass into pad rows either
    got_s = ragged_paged_attention_xla(
        q, kp, vp, pt, pos, scale=0.25,
        sinks=jnp.ones((q.shape[1],), jnp.float32),
    )
    assert np.asarray(got_s)[2].max() == 0.0


def test_pallas_gqa_kernel_matches_reference():
    q, keys, values, kp, vp, pt, pos = _paged_setup(seed=3)
    pos = pos.at[4].set(-1)
    from automodel_tpu.ops.pallas.ragged_paged_attention import (
        paged_attention_kernel,
    )

    want = ragged_paged_attention_xla(q, kp, vp, pt, pos, scale=0.25)
    got = paged_attention_kernel(q, kp, vp, pt, pos, scale=0.25)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)
    # soft-cap rides the kernel too (gemma-style decode)
    want_c = ragged_paged_attention_xla(q, kp, vp, pt, pos, scale=0.25, soft_cap=8.0)
    got_c = paged_attention_kernel(q, kp, vp, pt, pos, scale=0.25, soft_cap=8.0)
    np.testing.assert_allclose(np.asarray(got_c), np.asarray(want_c), atol=1e-5)


def test_pallas_mla_kernel_matches_reference():
    rng = np.random.default_rng(4)
    T, n, r, dr, ps, P, N = 5, 4, 16, 8, 4, 4, 9
    qa = jnp.asarray(rng.normal(size=(T, n, r)), jnp.float32)
    qr = jnp.asarray(rng.normal(size=(T, n, dr)), jnp.float32)
    cp = jnp.asarray(rng.normal(size=(N + 1, ps, r)), jnp.float32)
    krp = jnp.asarray(rng.normal(size=(N + 1, ps, dr)), jnp.float32)
    pt = jnp.asarray(rng.integers(0, N, (T, P)), jnp.int32)
    pos = jnp.asarray([0, 3, -1, 11, 15], jnp.int32)
    from automodel_tpu.ops.pallas.ragged_paged_attention import (
        paged_mla_attention_kernel,
    )

    want = ragged_paged_mla_attention_xla(qa, qr, cp, krp, pt, pos, scale=0.2)
    got = paged_mla_attention_kernel(qa, qr, cp, krp, pt, pos, scale=0.2)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)
    assert np.asarray(got)[2].max() == 0.0  # pad row


def test_dispatch_falls_back_for_kernel_unsupported_features():
    """Windows/sinks raise NotImplementedError from the kernel entry so the
    dispatcher (impl='pallas') silently takes the XLA path — the flash
    dispatch contract."""
    q, keys, values, kp, vp, pt, pos = _paged_setup(seed=5)
    from automodel_tpu.ops.pallas.ragged_paged_attention import (
        paged_attention_kernel,
    )

    with pytest.raises(NotImplementedError):
        paged_attention_kernel(q, kp, vp, pt, pos, scale=0.25, window=jnp.int32(4))
    with pytest.raises(NotImplementedError):
        paged_attention_kernel(
            q, kp, vp, pt, pos, scale=0.25,
            sinks=jnp.zeros((q.shape[1],), jnp.float32),
        )
    got = ragged_paged_attention(
        q, kp, vp, pt, pos, scale=0.25, window=jnp.int32(4), impl="pallas",
    )
    want = ragged_paged_attention_xla(q, kp, vp, pt, pos, scale=0.25, window=jnp.int32(4))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-6)
