"""Real VLM SFT collator tests (image preprocessing + chat layout).

Hermetic: synthetic images (inline arrays / .npy), stub tokenizer — the
analog of the reference's vlm collate_fns unit tier (reference:
tests/unit_tests/datasets/vlm/)."""

import json

import numpy as np
import pytest

from automodel_tpu.datasets.vlm_collators import (
    CLIP_MEAN,
    CLIP_STD,
    IGNORE_INDEX,
    VLMSFTDatasetConfig,
    preprocess_image,
    resize_bilinear,
)


class StubTokenizer:
    eos_token_id = 2
    pad_token_id = 0

    def encode(self, text, add_special_tokens=False):
        return [3 + (ord(c) % 50) for c in text]


def test_resize_bilinear_identity_and_downscale():
    img = np.random.default_rng(0).random((8, 8, 3)).astype(np.float32)
    np.testing.assert_array_equal(resize_bilinear(img, 8), img)
    small = resize_bilinear(img, 4)
    assert small.shape == (4, 4, 3)
    # downscale preserves the global mean approximately
    assert abs(small.mean() - img.mean()) < 0.05


def test_preprocess_normalizes_with_clip_stats(tmp_path):
    img = np.ones((6, 6, 3), np.float32) * 0.5
    p = tmp_path / "img.npy"
    np.save(p, img)
    out = preprocess_image(str(p), 6)
    np.testing.assert_allclose(
        out, np.broadcast_to((0.5 - CLIP_MEAN) / CLIP_STD, (6, 6, 3)), rtol=1e-5
    )


def test_vlm_sft_layout_and_masking(tmp_path):
    rows = [
        {"image": np.full((4, 4, 3), 0.3).tolist(),
         "prompt": "what", "response": "cat"},
        {"image": np.full((4, 4, 3), 0.7).tolist(),
         "conversations": [
             {"role": "user", "content": "a"},
             {"role": "assistant", "content": "b"},
             {"role": "user", "content": "c"},
             {"role": "assistant", "content": "d"},
         ]},
    ]
    p = tmp_path / "vlm.jsonl"
    p.write_text("\n".join(json.dumps(r) for r in rows))
    cfg = VLMSFTDatasetConfig(
        data_path=str(p), image_size=8, num_patches=4, image_token_id=99,
        seq_len=64,
    )
    ds = cfg.build(StubTokenizer())
    assert len(ds) == 2

    s = ds[0]
    assert s["pixel_values"].shape == (8, 8, 3)
    assert s["input_ids"].shape == (64,) and s["labels"].shape == (64,)
    # image span: exactly num_patches image tokens at the front, unsupervised
    assert (s["input_ids"][:4] == 99).all()
    assert (s["labels"][:3] == IGNORE_INDEX).all()
    # the user span is masked; the assistant span is supervised
    n_sup = (s["labels"] != IGNORE_INDEX).sum()
    assert n_sup > 0
    # supervised tokens = assistant prefix+content + eos
    asst_len = len(StubTokenizer().encode(" ASSISTANT: cat")) + 1
    assert n_sup == asst_len

    # multi-turn: both assistant turns supervised, both user turns masked
    s2 = ds[1]
    n_sup2 = (s2["labels"] != IGNORE_INDEX).sum()
    a1 = len(StubTokenizer().encode(" ASSISTANT: b"))
    a2 = len(StubTokenizer().encode(" ASSISTANT: d"))
    assert n_sup2 == a1 + a2 + 1  # + eos


def test_vlm_sft_image_marker_expands_in_place(tmp_path):
    """A `<image>` marker inside the prompt expands to num_patches image
    tokens AT THAT POSITION (not prepended), unsupervised."""
    rows = [{
        "image": np.full((4, 4, 3), 0.2).tolist(),
        "prompt": "look <image> here", "response": "ok",
    }]
    p = tmp_path / "vlm.jsonl"
    p.write_text("\n".join(json.dumps(r) for r in rows))
    cfg = VLMSFTDatasetConfig(
        data_path=str(p), image_size=8, num_patches=4, image_token_id=99,
        seq_len=64,
    )
    s = cfg.build(StubTokenizer())[0]
    ids = s["input_ids"]
    # patch block sits after the encoded "USER: look " prefix
    pre = len(StubTokenizer().encode("USER: look "))
    assert (ids[:pre] != 99).all()
    assert (ids[pre:pre + 4] == 99).all()
    assert (ids[pre + 4:] != 99).all()
    # and the literal marker text was never tokenized
    marker_toks = StubTokenizer().encode("<image>")
    window = list(ids[pre + 4: pre + 4 + len(marker_toks)])
    assert window != marker_toks


@pytest.mark.slow
def test_vlm_sft_feeds_recipe(tmp_path):
    """End-to-end: the real collator drives the VLM finetune recipe."""
    from automodel_tpu.cli.app import resolve_recipe_class
    from automodel_tpu.config import ConfigNode

    rows = [
        {"image": (np.random.default_rng(i).random((10, 10, 3))).tolist(),
         "prompt": f"q{i}", "response": f"answer {i}"}
        for i in range(16)
    ]
    p = tmp_path / "vlm.jsonl"
    p.write_text("\n".join(json.dumps(r) for r in rows))

    cfg = ConfigNode({
        "seed": 5, "recipe": "vlm_finetune", "run_dir": str(tmp_path),
        "auto_resume": False,
        "model": {
            "hf_config": {
                "architectures": ["LlavaForConditionalGeneration"],
                "image_token_index": 99,
                "text_config": {
                    "architectures": ["LlamaForCausalLM"],
                    "vocab_size": 128, "hidden_size": 32,
                    "intermediate_size": 64, "num_hidden_layers": 2,
                    "num_attention_heads": 4, "num_key_value_heads": 2,
                },
                "vision_config": {
                    "hidden_size": 16, "intermediate_size": 32,
                    "num_hidden_layers": 1, "num_attention_heads": 2,
                    "image_size": 8, "patch_size": 4, "num_channels": 3,
                },
            },
            "dtype": "float32", "remat_policy": "none",
        },
        "distributed": {"dp_shard": -1},
        "dataset": {
            "_target_": "automodel_tpu.datasets.vlm_collators.VLMSFTDatasetConfig",
            "data_path": str(p), "image_size": 8, "num_patches": 4,
            "image_token_id": 99, "seq_len": 32,
        },
        "tokenizer": None,
        "dataloader": {"microbatch_size": 8, "grad_acc_steps": 1},
        "optimizer": {"name": "adamw", "lr": 1e-3, "weight_decay": 0.0},
        "lr_scheduler": {"style": "constant", "warmup_steps": 0},
        "step_scheduler": {"max_steps": 2, "ckpt_every_steps": 1000},
        "checkpoint": {"enabled": False},
        "loss": {"chunk_size": 32},
    })

    r = resolve_recipe_class(cfg)(cfg)
    # recipes build datasets through cfg; hand the stub tokenizer in directly
    r._build_tokenizer = lambda: StubTokenizer()
    r.setup()
    r.run_train_validation_loop()
    recs = [json.loads(l) for l in open(tmp_path / "training.jsonl") if l.strip()]
    assert len(recs) == 2
    assert all(np.isfinite(x["loss"]) for x in recs)
