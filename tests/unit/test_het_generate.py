"""het_generate: KV-cache decode parity for the heterogeneous MoE engine.

The het engine (step3p5 / mimo-v2-flash / minimax-m3) decodes through
`inference/het_generate.py` — per-layer python-loop caches including the
block-sparse DSA index cache. Parity oracle: re-run the full het_moe
forward for every new token (the discipline of test_generate.py).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from automodel_tpu.inference.generate import GenerateConfig, generate
from automodel_tpu.models.moe_lm import het_moe
from automodel_tpu.models.registry import get_model_spec

# MiniMax-M3 shape (tests/unit/test_minimax_m3.py): gemma norms, partial
# rotary, sigmoid-routed MoE + shared expert, block-sparse DSA on layers 1-2
M3_TEXT_HF = {
    "architectures": ["MiniMaxM3SparseForCausalLM"],
    "model_type": "minimax_m3",
    "vocab_size": 128,
    "hidden_size": 32,
    "intermediate_size": 16,
    "dense_intermediate_size": 64,
    "shared_intermediate_size": 16,
    "num_hidden_layers": 3,
    "num_attention_heads": 4,
    "num_key_value_heads": 2,
    "head_dim": 8,
    "rotary_dim": 4,
    "rope_theta": 5000000.0,
    "use_gemma_norm": True,
    "use_qk_norm": True,
    "num_local_experts": 4,
    "num_experts_per_tok": 2,
    "n_shared_experts": 1,
    "scoring_func": "sigmoid",
    "use_routing_bias": True,
    "routed_scaling_factor": 2.0,
    "moe_layer_freq": [0, 1, 1],
    "sparse_attention_config": {
        "use_sparse_attention": True,
        "sparse_attention_freq": [0, 1, 1],
        "sparse_num_index_heads": 2,
        "sparse_index_dim": 8,
        "sparse_block_size": 4,
        "sparse_topk_blocks": 3,
        "sparse_init_block": 1,
        "sparse_local_block": 1,
        "sparse_score_type": "max",
    },
    "rms_norm_eps": 1e-6,
}


def _setup():
    spec = get_model_spec(M3_TEXT_HF)
    cfg = spec.config_from_hf(M3_TEXT_HF, dtype=jnp.float32, remat_policy="none")
    return cfg, het_moe.init(cfg, jax.random.key(0))


def _naive_greedy(params, cfg, ids, n):
    for _ in range(n):
        logits, _ = het_moe.forward(params, cfg, ids)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        ids = jnp.concatenate([ids, nxt[:, None]], axis=1)
    return ids


def test_het_greedy_matches_naive():
    """Sparse index cache + per-layer heterogeneity decode == full
    re-forward (also exercises the generate() HetMoEConfig dispatch)."""
    cfg, params = _setup()
    prompt = jnp.asarray(
        np.random.default_rng(5).integers(1, 128, (2, 7)), jnp.int32
    )
    fast = generate(
        params, cfg, prompt, jax.random.key(2), GenerateConfig(max_new_tokens=3)
    )
    slow = _naive_greedy(params, cfg, prompt, 3)
    np.testing.assert_array_equal(np.asarray(fast), np.asarray(slow))


@pytest.mark.slow
def test_het_eos_early_stop_pads_with_eos():
    cfg, params = _setup()
    prompt = jnp.asarray(
        np.random.default_rng(6).integers(1, 128, (1, 5)), jnp.int32
    )
    probe = generate(
        params, cfg, prompt, jax.random.key(0), GenerateConfig(max_new_tokens=3)
    )
    eos = int(probe[0, 5 + 1])  # second generated token
    out = generate(
        params, cfg, prompt, jax.random.key(0),
        GenerateConfig(max_new_tokens=6, eos_token_id=eos),
    )
    gen_tokens = np.asarray(out[0, 5:])
    hits = np.flatnonzero(gen_tokens == eos)
    assert len(hits) > 0
    assert (gen_tokens[hits[0]:] == eos).all()


@pytest.mark.slow
def test_het_temperature_sampling_valid_and_uses_shared_filter():
    """Sampled decode stays in-vocab and varies by key; the filter is the
    shared inference.sampling one (top_k=1 sampling == greedy)."""
    cfg, params = _setup()
    prompt = jnp.asarray(
        np.random.default_rng(7).integers(1, 128, (1, 4)), jnp.int32
    )
    g = GenerateConfig(max_new_tokens=4, temperature=1.0)
    a = generate(params, cfg, prompt, jax.random.key(1), g)
    b = generate(params, cfg, prompt, jax.random.key(2), g)
    assert ((np.asarray(a) >= 0) & (np.asarray(a) < 128)).all()
    assert not np.array_equal(np.asarray(a), np.asarray(b))
    topk1 = generate(
        params, cfg, prompt, jax.random.key(3),
        GenerateConfig(max_new_tokens=4, temperature=1.0, top_k=1),
    )
    greedy = generate(
        params, cfg, prompt, jax.random.key(4), GenerateConfig(max_new_tokens=4)
    )
    np.testing.assert_array_equal(np.asarray(topk1), np.asarray(greedy))
