from automodel_tpu.eval.tool_call_evaluator import evaluate_tool_calls, parse_tool_calls


def test_parse_formats():
    assert parse_tool_calls('<tool_call>{"name": "get_weather", "arguments": {"city": "Paris"}}</tool_call>') == [
        {"name": "get_weather", "arguments": {"city": "Paris"}}
    ]
    assert parse_tool_calls('```json\n{"name": "f", "arguments": {"x": 1}}\n```')[0]["name"] == "f"
    assert parse_tool_calls('{"name": "g", "arguments": "{\\"y\\": 2}"}')[0]["arguments"] == {"y": 2}
    assert parse_tool_calls("no calls here") == []


def test_evaluate_accuracy_levels():
    gold = [[{"name": "get_weather", "arguments": {"city": "Paris", "days": 3}}]]
    exact = ['<tool_call>{"name": "get_weather", "arguments": {"days": 3, "city": "Paris"}}</tool_call>']
    fuzzy = ['<tool_call>{"name": "get_weather", "arguments": {"city": " PARIS ", "days": "3"}}</tool_call>']
    wrong_args = ['<tool_call>{"name": "get_weather", "arguments": {"city": "London", "days": 3}}</tool_call>']
    wrong_name = ['<tool_call>{"name": "weather", "arguments": {"city": "Paris"}}</tool_call>']

    m = evaluate_tool_calls(exact, gold)
    assert m["exact_accuracy"] == 1.0 and m["name_accuracy"] == 1.0
    m = evaluate_tool_calls(fuzzy, gold)
    assert m["exact_accuracy"] == 0.0 and m["fuzzy_accuracy"] == 1.0
    m = evaluate_tool_calls(wrong_args, gold)
    assert m["name_accuracy"] == 1.0 and m["fuzzy_accuracy"] == 0.0
    m = evaluate_tool_calls(wrong_name, gold)
    assert m["name_accuracy"] == 0.0


def test_gold_with_string_arguments_normalized():
    gold = [[{"name": "f", "arguments": "{\"y\": 2}"}]]
    pred = ['<tool_call>{"name": "f", "arguments": {"y": 2}}</tool_call>']
    m = evaluate_tool_calls(pred, gold)
    assert m["exact_accuracy"] == 1.0
