"""DSA (DeepSeek sparse attention / lightning indexer) tests.

Parity strategy: the mask-based sparse path must equal dense MLA exactly
when index_topk >= S (every admissible key selected), the selection must
be a size-k subset of the causal mask, and the indexer must receive
gradient only through the KL aux (reference: components/models/
deepseek_v4/layers.py, kernels/sparse_attention.py).
"""

import dataclasses
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from automodel_tpu.models.llm.decoder import TransformerConfig
from automodel_tpu.ops.attention import make_attention_mask
from automodel_tpu.ops.dsa import indexer_scores, topk_select_mask

MLA_KW = dict(
    vocab_size=128, hidden_size=32, intermediate_size=48, num_layers=2,
    num_heads=4, num_kv_heads=4, attention_type="mla",
    mla_kv_lora_rank=16, mla_qk_nope_head_dim=8, mla_qk_rope_head_dim=8,
    mla_v_head_dim=8, dtype=jnp.float32, remat_policy="none",
)


def _mask(S):
    return make_attention_mask(S, S, causal=True)[None]


def test_topk_select_exact_k():
    rng = np.random.default_rng(0)
    B, S, k = 2, 12, 4
    scores = jnp.asarray(rng.normal(size=(B, S, S)), jnp.float32)
    sel = topk_select_mask(scores, _mask(S), k)
    sel = np.asarray(sel)
    base = np.asarray(jnp.broadcast_to(_mask(S), (B, S, S)))
    # subset of the causal mask
    assert not np.any(sel & ~base)
    counts = sel.sum(-1)
    admissible = base.sum(-1)
    # min(k, admissible) keys per query (ties can't inflate: scores are
    # continuous random)
    np.testing.assert_array_equal(counts, np.minimum(k, admissible))


def test_indexer_scores_shape_and_nonneg_heads():
    rng = np.random.default_rng(1)
    B, S, H, Hi, Di = 2, 8, 32, 4, 16
    x = jnp.asarray(rng.normal(size=(B, S, H)), jnp.float32)
    ip = {
        "wq": {"kernel": jnp.asarray(rng.normal(size=(H, Hi * Di)), jnp.float32)},
        "wk": {"kernel": jnp.asarray(rng.normal(size=(H, Di)), jnp.float32)},
        "wgate": {"kernel": jnp.asarray(rng.normal(size=(H, Hi)), jnp.float32)},
    }
    pos = jnp.broadcast_to(jnp.arange(S)[None], (B, S))
    s = indexer_scores(x, ip, Hi, Di, pos, None)
    assert s.shape == (B, S, S)
    assert bool(jnp.isfinite(s).all())


@pytest.mark.slow
def test_sparse_equals_dense_when_topk_covers_all():
    from automodel_tpu.models.llm import mla
    from automodel_tpu.models.llm.decoder import init_attention_layers
    from automodel_tpu.ops.rope import rope_frequencies

    S = 10
    cfg = TransformerConfig(**MLA_KW, dsa_index_topk=S)
    lp_stack = init_attention_layers(cfg, jax.random.key(0), 1)
    lp = jax.tree.map(lambda p: p[0], lp_stack)
    h = jax.random.normal(jax.random.key(1), (2, S, cfg.hidden_size), jnp.float32)
    pos = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32)[None], (2, S))
    inv_freq = rope_frequencies(cfg.rope_dim, cfg.rope_theta)
    ident = lambda a, axes: a

    sparse_out, aux, _sel = mla.mla_sparse_attention_block(
        h, lp, cfg, pos, None, inv_freq, ident
    )
    dense_cfg = dataclasses.replace(cfg, dsa_index_topk=None)
    dense_out = mla.mla_attention_block(
        h, lp, dense_cfg, pos, None, inv_freq, ident, None
    )
    np.testing.assert_allclose(
        np.asarray(sparse_out), np.asarray(dense_out), atol=2e-5
    )
    assert np.isfinite(float(aux))


@pytest.mark.slow
def test_indexer_gets_gradient_only_via_kl():
    from automodel_tpu.models.llm import mla
    from automodel_tpu.models.llm.decoder import init_attention_layers
    from automodel_tpu.ops.rope import rope_frequencies

    S = 12
    cfg = TransformerConfig(**MLA_KW, dsa_index_topk=4, dsa_indexer_loss_coeff=0.1)
    lp_stack = init_attention_layers(cfg, jax.random.key(0), 1)
    lp = jax.tree.map(lambda p: p[0], lp_stack)
    h = jax.random.normal(jax.random.key(1), (1, S, cfg.hidden_size), jnp.float32)
    pos = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32)[None], (1, S))
    inv_freq = rope_frequencies(cfg.rope_dim, cfg.rope_theta)
    ident = lambda a, axes: a

    def loss_with_aux(lp):
        out, aux, _ = mla.mla_sparse_attention_block(h, lp, cfg, pos, None, inv_freq, ident)
        return jnp.sum(out**2) * 0.0 + aux  # only the aux path

    g = jax.grad(loss_with_aux)(lp)
    gnorm = jnp.sqrt(sum(jnp.sum(x**2) for x in jax.tree.leaves(g["indexer"])))
    assert float(gnorm) > 0.0  # indexer learns from the KL term

    def loss_no_aux(lp):
        out, aux, _ = mla.mla_sparse_attention_block(h, lp, cfg, pos, None, inv_freq, ident)
        return jnp.sum(out.astype(jnp.float32) ** 2)

    g2 = jax.grad(loss_no_aux)(lp)
    gnorm2 = jnp.sqrt(sum(jnp.sum(x**2) for x in jax.tree.leaves(g2["indexer"])))
    assert float(gnorm2) == 0.0  # hard top-k passes no gradient


def test_indexer_adapter_roundtrip_and_optional():
    """Our consolidated exports round-trip indexer weights; checkpoints
    without them (V3-era / reference-compressed layout) load with the
    indexer leaf simply absent."""
    from automodel_tpu.checkpoint.hf_adapter import DenseDecoderAdapter

    cfg = TransformerConfig(**MLA_KW, dsa_index_topk=4, mla_q_lora_rank=8)
    from automodel_tpu.models.llm import decoder

    params = decoder.init(cfg, jax.random.key(0))
    ad = DenseDecoderAdapter(cfg)
    sd = dict(ad.to_hf(params))
    assert "model.layers.0.self_attn.indexer.wq.weight" in sd
    p2 = ad.from_hf(lambda k: sd[k])
    np.testing.assert_allclose(
        np.asarray(p2["layers"]["indexer"]["wq"]["kernel"]),
        np.asarray(params["layers"]["indexer"]["wq"]["kernel"]),
        rtol=1e-6,
    )
    # V3-era checkpoint: drop indexer keys → leaf absent, no raise
    sd_v3 = {k: v for k, v in sd.items() if "indexer" not in k}
    p3 = ad.from_hf(lambda k: sd_v3[k])
    assert "indexer" not in p3["layers"]


@pytest.mark.slow
def test_dsv4_recipe_smoke(tmp_path):
    from automodel_tpu.cli.app import resolve_recipe_class
    from tests.unit.test_recipe import _smoke_cfg

    cfg = _smoke_cfg(tmp_path)
    cfg.set("model.hf_config", {
        "architectures": ["DeepseekV4ForCausalLM"],
        "vocab_size": 128, "hidden_size": 32, "intermediate_size": 64,
        "num_hidden_layers": 2, "num_attention_heads": 4,
        "num_key_value_heads": 4, "first_k_dense_replace": 1,
        "n_routed_experts": 4, "num_experts_per_tok": 2,
        "moe_intermediate_size": 16, "n_shared_experts": 1,
        "kv_lora_rank": 16, "qk_nope_head_dim": 8, "qk_rope_head_dim": 8,
        "v_head_dim": 8,
        "index_topk": 8, "index_n_heads": 2, "index_head_dim": 16,
    })
    cfg.set("checkpoint.enabled", False)
    cfg.set("step_scheduler.max_steps", 3)
    r = resolve_recipe_class(cfg)(cfg)
    r.setup()
    assert r.model_cfg.dsa_index_topk == 8
    r.run_train_validation_loop()
    recs = [json.loads(l) for l in open(tmp_path / "training.jsonl") if l.strip()]
    assert len(recs) == 3
    assert all(np.isfinite(x["loss"]) for x in recs)


@pytest.mark.slow
def test_chunked_sparse_matches_oracle():
    """The blockwise two-phase path == the dense-mask oracle (fwd + the
    indexer-KL aux), including gradient routing (indexer only via KL)."""
    import dataclasses as dc

    from automodel_tpu.models.llm import mla
    from automodel_tpu.models.llm.decoder import init_attention_layers
    from automodel_tpu.ops.rope import rope_frequencies

    S = 48
    base = TransformerConfig(
        **MLA_KW, dsa_index_topk=8, dsa_indexer_loss_coeff=0.1,
        mla_q_lora_rank=8,
    )
    cfg_o = dc.replace(base, dsa_impl="oracle")
    cfg_c = dc.replace(base, dsa_impl="chunked", dsa_query_block=16)
    lp_stack = init_attention_layers(cfg_o, jax.random.key(0), 1)
    lp = jax.tree.map(lambda p: p[0], lp_stack)
    h = jax.random.normal(jax.random.key(1), (2, S, cfg_o.hidden_size), jnp.float32)
    pos = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32)[None], (2, S))
    seg = jnp.concatenate(
        [jnp.zeros((2, S // 2), jnp.int32), jnp.ones((2, S - S // 2), jnp.int32)], 1
    )
    inv_freq = rope_frequencies(cfg_o.rope_dim, cfg_o.rope_theta)
    ident = lambda a, axes: a

    o_out, o_aux, _ = mla.mla_sparse_attention_block(h, lp, cfg_o, pos, seg, inv_freq, ident)
    c_out, c_aux, c_idx = mla.mla_sparse_attention_block(h, lp, cfg_c, pos, seg, inv_freq, ident)
    np.testing.assert_allclose(np.asarray(o_out), np.asarray(c_out), rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(float(o_aux), float(c_aux), rtol=2e-3)
    assert c_idx.shape == (2, S, 8)

    # indexer learns only from the KL term in the chunked path too
    def loss_no_aux(lp):
        out, aux, _ = mla.mla_sparse_attention_block(h, lp, cfg_c, pos, seg, inv_freq, ident)
        return jnp.sum(out.astype(jnp.float32) ** 2)

    g = jax.grad(loss_no_aux)(lp)
    gnorm = jnp.sqrt(sum(jnp.sum(x**2) for x in jax.tree.leaves(g["indexer"])))
    assert float(gnorm) == 0.0

    def loss_aux(lp):
        out, aux, _ = mla.mla_sparse_attention_block(h, lp, cfg_c, pos, seg, inv_freq, ident)
        return aux

    g2 = jax.grad(loss_aux)(lp)
    gnorm2 = jnp.sqrt(sum(jnp.sum(x**2) for x in jax.tree.leaves(g2["indexer"])))
    assert float(gnorm2) > 0.0


@pytest.mark.slow
def test_chunked_sparse_glm_index_share_parity():
    """IndexShare carries indices in the chunked path; shared-layer reuse
    matches the oracle's mask reuse."""
    import dataclasses as dc

    from automodel_tpu.models.llm import mla
    from automodel_tpu.models.llm.decoder import init_attention_layers
    from automodel_tpu.ops.rope import rope_frequencies

    S = 32
    base = TransformerConfig(
        **MLA_KW, dsa_index_topk=6, mla_q_lora_rank=8,
        dsa_indexer_style="glm", dsa_index_n_heads=2, dsa_index_head_dim=16,
    )
    cfg_o = dc.replace(base, dsa_impl="oracle")
    cfg_c = dc.replace(base, dsa_impl="chunked", dsa_query_block=16)
    lp_stack = init_attention_layers(cfg_o, jax.random.key(0), 1)
    lp = jax.tree.map(lambda p: p[0], lp_stack)
    h = jax.random.normal(jax.random.key(1), (1, S, cfg_o.hidden_size), jnp.float32)
    pos = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32)[None], (1, S))
    inv_freq = rope_frequencies(cfg_o.rope_dim, cfg_o.rope_theta)
    ident = lambda a, axes: a

    o_out, _, _ = mla.mla_sparse_attention_block(h, lp, cfg_o, pos, None, inv_freq, ident)
    c_out, _, idx = mla.mla_sparse_attention_block(h, lp, cfg_c, pos, None, inv_freq, ident)
    np.testing.assert_allclose(np.asarray(o_out), np.asarray(c_out), rtol=2e-4, atol=2e-5)

    # a "shared" call (flag 0) with prev idx must reproduce the full call
    flag0 = jnp.zeros((), jnp.int32)
    s_out, s_aux, s_idx = mla.mla_sparse_attention_block(
        h, lp, cfg_c, pos, None, inv_freq, ident, prev_sel=idx, indexer_flag=flag0
    )
    np.testing.assert_allclose(np.asarray(s_out), np.asarray(c_out), rtol=1e-5, atol=1e-6)
    np.testing.assert_array_equal(np.asarray(s_idx), np.asarray(idx))
    assert float(s_aux) == 0.0


@pytest.mark.slow
def test_chunked_sparse_memory_scales_blockwise():
    """Compiled peak temps: the chunked path must not materialize (S,S)
    score tensors — compare XLA's memory analysis vs the oracle."""
    import dataclasses as dc

    from automodel_tpu.models.llm import mla
    from automodel_tpu.models.llm.decoder import init_attention_layers
    from automodel_tpu.ops.rope import rope_frequencies

    S = 1024
    base = TransformerConfig(**MLA_KW, dsa_index_topk=64, mla_q_lora_rank=8)
    cfg_o = dc.replace(base, dsa_impl="oracle")
    cfg_c = dc.replace(base, dsa_impl="chunked", dsa_query_block=64)
    lp_stack = init_attention_layers(cfg_o, jax.random.key(0), 1)
    lp = jax.tree.map(lambda p: p[0], lp_stack)
    pos = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32)[None], (1, S))
    inv_freq = rope_frequencies(cfg_o.rope_dim, cfg_o.rope_theta)
    ident = lambda a, axes: a
    h_shape = jax.ShapeDtypeStruct((1, S, cfg_o.hidden_size), jnp.float32)

    def temp_bytes(cfg):
        f = jax.jit(
            lambda h: mla.mla_sparse_attention_block(h, lp, cfg, pos, None, inv_freq, ident)[0]
        )
        mem = f.lower(h_shape).compile().memory_analysis()
        return int(mem.temp_size_in_bytes)

    t_o, t_c = temp_bytes(cfg_o), temp_bytes(cfg_c)
    # oracle carries (B,Hi,S,S)+(B,S,S) fp32 temps; chunked O(S·block)
    assert t_c < t_o / 4, (t_o, t_c)
