"""Long-tail model families: baichuan, qwen3.5(-moe) (+ later additions).

Parity strategy: no torch oracle exists in-env for these architectures
(transformers 4.57 predates them / never shipped baichuan natively), so the
tests pin the checkpoint-layout contracts (adapter round-trips through the
exact HF tensor layout) and the architecture semantics (NormHead, separate
GDN projections) the reference implements.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from automodel_tpu.models.llm import decoder
from automodel_tpu.models.registry import get_model_spec


BAICHUAN_HF = {
    "architectures": ["BaichuanForCausalLM"],
    "model_type": "baichuan",
    "vocab_size": 128, "hidden_size": 32, "intermediate_size": 64,
    "num_hidden_layers": 2, "num_attention_heads": 4,
    "rms_norm_eps": 1e-6,
}


def test_baichuan_registry_and_normhead():
    spec = get_model_spec(BAICHUAN_HF)
    cfg = spec.config_from_hf(BAICHUAN_HF, dtype=jnp.float32, remat_policy="none")
    assert cfg.num_kv_heads == cfg.num_heads  # MHA
    assert cfg.normalized_lm_head
    params = decoder.init(cfg, jax.random.key(0))
    # NormHead: scaling lm_head rows must NOT change logits (normalized away)
    ids = jax.random.randint(jax.random.key(1), (2, 8), 0, 128)
    base = decoder.forward(params, cfg, ids)
    scaled = dict(params)
    scaled["lm_head"] = {"kernel": params["lm_head"]["kernel"] * 7.5}
    again = decoder.forward(scaled, cfg, ids)
    np.testing.assert_allclose(np.asarray(base), np.asarray(again), atol=1e-5)


def test_baichuan_adapter_w_pack_roundtrip():
    from automodel_tpu.checkpoint.hf_adapter import get_adapter

    spec = get_model_spec(BAICHUAN_HF)
    cfg = spec.config_from_hf(BAICHUAN_HF, dtype=jnp.float32, remat_policy="none")
    params = decoder.init(cfg, jax.random.key(0))
    ad = get_adapter(spec.adapter_name, cfg, **spec.adapter_kwargs)
    sd = dict(ad.to_hf(params))
    assert "model.layers.0.self_attn.W_pack.weight" in sd
    assert sd["model.layers.0.self_attn.W_pack.weight"].shape == (3 * 32, 32)
    assert not any("q_proj" in k for k in sd)
    p2 = ad.from_hf(lambda k: sd[k])
    ids = jax.random.randint(jax.random.key(2), (2, 8), 0, 128)
    o1 = decoder.forward(params, cfg, ids)
    o2 = decoder.forward(jax.tree.map(jnp.asarray, p2), cfg, ids)
    np.testing.assert_allclose(np.asarray(o1), np.asarray(o2), atol=1e-5)


QWEN3_5_MOE_HF = {
    "architectures": ["Qwen3_5MoeForConditionalGeneration"],
    "model_type": "qwen3_5_moe",
    "text_config": {
        "vocab_size": 128, "hidden_size": 32, "intermediate_size": 64,
        "num_hidden_layers": 4, "num_attention_heads": 4,
        "num_key_value_heads": 2, "head_dim": 8,
        "layer_types": [
            "linear_attention", "full_attention",
            "linear_attention", "full_attention",
        ],
        "linear_num_value_heads": 4, "linear_num_key_heads": 2,
        "linear_key_head_dim": 8, "linear_value_head_dim": 8,
        "num_experts": 4, "num_experts_per_tok": 2,
        "moe_intermediate_size": 16, "shared_expert_intermediate_size": 16,
        "norm_topk_prob": True, "rope_theta": 10000.0,
    },
}


@pytest.mark.slow
def test_qwen3_5_moe_adapter_roundtrip():
    """to_hf emits the Qwen3.5 layout (separate GDN projections, stacked
    experts, language_model prefix) and from_hf inverts it exactly."""
    from automodel_tpu.checkpoint.hf_adapter import get_adapter
    from automodel_tpu.models.hybrid import qwen3_5 as q35

    spec = get_model_spec(QWEN3_5_MOE_HF)
    cfg = spec.config_from_hf(QWEN3_5_MOE_HF, remat_policy="none")
    assert cfg.moe is not None
    params = q35.init(cfg, jax.random.key(0))
    ad = get_adapter(spec.adapter_name, cfg, **spec.adapter_kwargs)
    sd = dict(ad.to_hf(params))
    pre = "model.language_model."
    assert pre + "layers.0.linear_attn.in_proj_qkv.weight" in sd
    assert pre + "layers.0.linear_attn.in_proj_z.weight" in sd
    assert pre + "layers.0.linear_attn.in_proj_b.weight" in sd
    assert pre + "layers.0.linear_attn.in_proj_a.weight" in sd
    assert not any("in_proj_qkvz" in k for k in sd)
    assert sd[pre + "layers.0.mlp.experts.gate_up_proj"].shape == (4, 32, 32)
    assert sd[pre + "layers.0.mlp.experts.down_proj"].shape == (4, 32, 16)
    p2 = ad.from_hf(lambda k: np.asarray(sd[k]))
    ids = jax.random.randint(jax.random.key(1), (2, 16), 0, 128)
    o1, _ = q35.forward(params, cfg, ids)
    o2, _ = q35.forward(jax.tree.map(jnp.asarray, p2), cfg, ids)
    np.testing.assert_allclose(np.asarray(o1), np.asarray(o2), atol=1e-5)


@pytest.mark.slow
def test_qwen3_5_dense_config():
    hf = {
        "architectures": ["Qwen3_5ForCausalLM"],
        "vocab_size": 128, "hidden_size": 32, "intermediate_size": 64,
        "num_hidden_layers": 2, "num_attention_heads": 4,
        "num_key_value_heads": 2, "head_dim": 8,
        "layer_types": ["linear_attention", "full_attention"],
        "linear_num_value_heads": 4, "linear_num_key_heads": 2,
        "linear_key_head_dim": 8, "linear_value_head_dim": 8,
    }
    spec = get_model_spec(hf)
    cfg = spec.config_from_hf(hf, remat_policy="none")
    assert cfg.moe is None
    from automodel_tpu.models.hybrid import qwen3_5 as q35

    params = q35.init(cfg, jax.random.key(0))
    ids = jax.random.randint(jax.random.key(1), (2, 16), 0, 128)
    out = q35.forward(params, cfg, ids)
    assert np.isfinite(np.asarray(out)).all()


GLM_DSA_HF = {
    "architectures": ["GlmMoeDsaForCausalLM"],
    "model_type": "glm_moe_dsa",
    "vocab_size": 128, "hidden_size": 32, "intermediate_size": 64,
    "num_hidden_layers": 2, "num_attention_heads": 4,
    "num_key_value_heads": 4,
    "n_routed_experts": 4, "n_shared_experts": 1,
    "num_experts_per_tok": 2, "moe_intermediate_size": 16,
    "first_k_dense_replace": 0, "norm_topk_prob": True,
    "routed_scaling_factor": 1.0,
    "kv_lora_rank": 16, "q_lora_rank": 12,
    "qk_nope_head_dim": 8, "qk_rope_head_dim": 8, "v_head_dim": 8,
    "index_topk": 6, "index_n_heads": 2, "index_head_dim": 16,
    "indexer_types": ["full", "shared"],
}


def _glm_dsa_setup():
    from automodel_tpu.models.moe_lm import decoder as moe_decoder

    spec = get_model_spec(GLM_DSA_HF)
    cfg = spec.config_from_hf(GLM_DSA_HF, dtype=jnp.float32, remat_policy="none")
    params = moe_decoder.init(cfg, jax.random.key(0))
    return spec, cfg, params, moe_decoder


@pytest.mark.slow
def test_glm_dsa_index_share_ignores_shared_layer_indexer():
    """IndexShare: a "shared" layer reuses the previous full layer's top-k,
    so zeroing its own indexer weights must not change the output (while
    zeroing it under all-"full" types must)."""
    import dataclasses

    spec, cfg, params, moe_decoder = _glm_dsa_setup()
    assert cfg.dsa_indexer_style == "glm"
    assert cfg.dsa_indexer_types == ("full", "shared")
    ids = jax.random.randint(jax.random.key(1), (2, 12), 0, 128)

    def zero_layer2_indexer(p):
        p = jax.tree.map(lambda x: x, p)  # copy
        p["moe_layers"]["indexer"] = jax.tree.map(
            lambda x: x.at[1].set(0.0), p["moe_layers"]["indexer"]
        )
        return p

    base, _ = moe_decoder.forward(params, cfg, ids)
    zeroed, _ = moe_decoder.forward(zero_layer2_indexer(params), cfg, ids)
    np.testing.assert_allclose(np.asarray(base), np.asarray(zeroed), atol=1e-6)

    cfg_full = dataclasses.replace(cfg, dsa_indexer_types=("full", "full"))
    base_f, _ = moe_decoder.forward(params, cfg_full, ids)
    zeroed_f, _ = moe_decoder.forward(zero_layer2_indexer(params), cfg_full, ids)
    assert np.abs(np.asarray(base_f) - np.asarray(zeroed_f)).max() > 1e-6


@pytest.mark.slow
def test_glm_dsa_adapter_roundtrip_index_share():
    """Export omits indexer keys for shared layers (matching HF); import
    zero-fills them; the round-trip reproduces logits exactly."""
    from automodel_tpu.checkpoint.hf_adapter import get_adapter

    spec, cfg, params, moe_decoder = _glm_dsa_setup()
    ad = get_adapter(spec.adapter_name, cfg, **spec.adapter_kwargs)
    sd = dict(ad.to_hf(params))
    assert "model.layers.0.self_attn.indexer.wq_b.weight" in sd
    assert "model.layers.0.self_attn.indexer.k_norm.bias" in sd
    assert not any("layers.1.self_attn.indexer" in k for k in sd)
    p2 = ad.from_hf(lambda k: np.asarray(sd[k]))
    ids = jax.random.randint(jax.random.key(2), (2, 12), 0, 128)
    o1, _ = moe_decoder.forward(params, cfg, ids)
    o2, _ = moe_decoder.forward(jax.tree.map(jnp.asarray, p2), cfg, ids)
    np.testing.assert_allclose(np.asarray(o1), np.asarray(o2), atol=1e-5)


GEMMA4_HF = {
    "architectures": ["Gemma4ForConditionalGeneration"],
    "model_type": "gemma4",
    "text_config": {
        "vocab_size": 128, "hidden_size": 32, "intermediate_size": 64,
        "num_hidden_layers": 4, "num_attention_heads": 4,
        "num_key_value_heads": 2, "head_dim": 8,
        "layer_types": [
            "sliding_attention", "full_attention",
            "sliding_attention", "full_attention",
        ],
        "sliding_window": 8, "rope_theta": 1000000.0,
        "rope_local_base_freq": 10000.0, "query_pre_attn_scalar": 8,
        "num_kv_shared_layers": 2,
        "num_experts": 4, "top_k_experts": 2, "moe_intermediate_size": 16,
        "rms_norm_eps": 1e-6,
    },
    "tie_word_embeddings": True,
}


def _gemma4_setup():
    from automodel_tpu.models.moe_lm import gemma4

    spec = get_model_spec(GEMMA4_HF)
    cfg = spec.config_from_hf(GEMMA4_HF, dtype=jnp.float32, remat_policy="none")
    params = gemma4.init(cfg, jax.random.key(0))
    return spec, cfg, params, gemma4


@pytest.mark.slow
def test_gemma4_forward_and_kv_sharing():
    """Layers 2/3 share layer 0/1's K/V (same-type): zeroing a shared
    layer's k/v kernels must not change the output."""
    spec, cfg, params, gemma4 = _gemma4_setup()
    assert cfg.num_kv_shared_layers == 2
    assert cfg.layer_types == ("sliding", "global", "sliding", "global")
    ids = jax.random.randint(jax.random.key(1), (2, 16), 0, 128)
    out, aux, stats = gemma4.forward(params, cfg, ids, return_stats=True)
    assert np.isfinite(np.asarray(out)).all()
    assert stats["tokens_per_expert"].shape == (4, 4)
    # every token routes to exactly top-k experts per layer
    np.testing.assert_allclose(
        np.asarray(stats["tokens_per_expert"]).sum(-1),
        2 * 16 * cfg.moe.experts_per_token,
    )

    zeroed = jax.tree.map(lambda x: x, params)
    for pk in ("k_proj", "v_proj"):
        zeroed["layers"][pk]["kernel"] = (
            zeroed["layers"][pk]["kernel"].at[2:].set(0.0)
        )
    out2, _, _ = gemma4.forward(zeroed, cfg, ids, return_stats=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(out2), atol=1e-6)


@pytest.mark.slow
def test_gemma4_adapter_roundtrip():
    from automodel_tpu.checkpoint.hf_adapter import get_adapter

    spec, cfg, params, gemma4 = _gemma4_setup()
    ad = get_adapter(spec.adapter_name, cfg, **spec.adapter_kwargs)
    sd = dict(ad.to_hf(params))
    pre = "model.language_model."
    assert pre + "layers.0.self_attn.k_proj.weight" in sd
    assert pre + "layers.0.router.scale" in sd
    assert pre + "layers.0.moe.gate_up_proj" in sd
    assert sd[pre + "layers.0.moe.gate_up_proj"].shape == (4, 32, 32)
    assert sd[pre + "layers.0.moe.down_proj"].shape == (4, 32, 16)
    # kv-shared layers export no k/v keys (matching HF)
    assert pre + "layers.2.self_attn.k_proj.weight" not in sd
    assert pre + "layers.3.self_attn.v_proj.weight" not in sd
    p2 = ad.from_hf(lambda k: np.asarray(sd[k]))
    ids = jax.random.randint(jax.random.key(2), (2, 16), 0, 128)
    o1, _, _ = gemma4.forward(params, cfg, ids, return_stats=True)
    o2, _, _ = gemma4.forward(
        jax.tree.map(jnp.asarray, p2), cfg, ids, return_stats=True
    )
    np.testing.assert_allclose(np.asarray(o1), np.asarray(o2), atol=1e-5)


@pytest.mark.slow
def test_gemma4_recipe_trains(tmp_path):
    import json

    from automodel_tpu.cli.app import resolve_recipe_class
    from tests.unit.test_recipe import _smoke_cfg

    cfg = _smoke_cfg(tmp_path)
    cfg.set("model.hf_config", GEMMA4_HF)
    cfg.set("distributed", {"dp_shard": -1, "ep": 2})
    cfg.set("checkpoint.enabled", False)
    cfg.set("step_scheduler.max_steps", 3)
    r = resolve_recipe_class(cfg)(cfg)
    r.setup()
    assert r.is_moe
    r.run_train_validation_loop()
    recs = [json.loads(l) for l in open(tmp_path / "training.jsonl") if l.strip()]
    assert len(recs) == 3
    assert all(np.isfinite(x["loss"]) for x in recs)


LING_HF = {
    "architectures": ["BailingMoeV2ForCausalLM"],
    "model_type": "bailing_moe",
    "vocab_size": 128, "hidden_size": 32, "intermediate_size": 64,
    "num_hidden_layers": 3, "num_attention_heads": 4,
    "num_key_value_heads": 2, "head_dim": 8,
    "use_qk_norm": True, "partial_rotary_factor": 0.5,
    "num_experts": 4, "num_shared_experts": 1, "num_experts_per_tok": 2,
    "n_group": 2, "topk_group": 2, "moe_intermediate_size": 16,
    "first_k_dense_replace": 1, "score_function": "sigmoid",
    "routed_scaling_factor": 1.0, "norm_topk_prob": True,
    "moe_router_enable_expert_bias": True,
}


@pytest.mark.slow
def test_ling_v2_adapter_fused_qkv_roundtrip():
    """Ling 2.0 (BailingMoeV2): fused query_key_value / attention.dense /
    word_embeddings naming round-trips exactly."""
    from automodel_tpu.checkpoint.hf_adapter import get_adapter
    from automodel_tpu.models.moe_lm import decoder as moe_decoder

    spec = get_model_spec(LING_HF)
    cfg = spec.config_from_hf(LING_HF, dtype=jnp.float32, remat_policy="none")
    assert cfg.qk_norm and cfg.partial_rotary_factor == 0.5
    assert cfg.first_k_dense == 1
    assert cfg.moe.gate_bias_update_speed > 0
    params = moe_decoder.init(cfg, jax.random.key(0))
    ad = get_adapter(spec.adapter_name, cfg, **spec.adapter_kwargs)
    sd = dict(ad.to_hf(params))
    assert "model.word_embeddings.weight" in sd
    assert sd["model.layers.0.attention.query_key_value.weight"].shape == (4 * 8 + 2 * 2 * 8, 32)
    assert "model.layers.0.attention.dense.weight" in sd
    assert "model.layers.0.attention.query_layernorm.weight" in sd
    assert "model.layers.1.mlp.gate.expert_bias" in sd
    assert "model.layers.1.mlp.shared_experts.gate_proj.weight" in sd
    assert not any("q_proj" in k for k in sd)
    p2 = ad.from_hf(lambda k: np.asarray(sd[k]))
    ids = jax.random.randint(jax.random.key(1), (2, 12), 0, 128)
    o1, _ = moe_decoder.forward(params, cfg, ids)
    o2, _ = moe_decoder.forward(jax.tree.map(jnp.asarray, p2), cfg, ids)
    np.testing.assert_allclose(np.asarray(o1), np.asarray(o2), atol=1e-5)


STEP35_HF = {
    "architectures": ["Step3p5ForCausalLM"],
    "model_type": "step3p5",
    "vocab_size": 128, "hidden_size": 32, "intermediate_size": 64,
    "num_hidden_layers": 4, "num_attention_heads": 4,
    "num_attention_groups": 2, "head_dim": 8,
    "attention_other_setting": {"num_attention_heads": 2, "num_attention_groups": 1},
    "layer_types": [
        "full_attention", "sliding_attention",
        "sliding_attention", "full_attention",
    ],
    "sliding_window": 8,
    "rope_theta": [10000.0, 5000.0, 5000.0, 10000.0],
    "partial_rotary_factors": [1.0, 0.5, 0.5, 1.0],
    "use_rope_layers": [True, True, False, True],
    "use_head_wise_attn_gate": True,
    "moe_layers_enum": [1, 3],
    "moe_num_experts": 4, "moe_top_k": 2, "moe_intermediate_size": 16,
    "moe_router_activation": "sigmoid", "use_moe_router_bias": True,
    "share_expert_dims": [16, 16, 16, 16],
    "rms_norm_eps": 1e-5,
}

MIMO_HF = {
    "architectures": ["MiMoV2FlashForCausalLM"],
    "model_type": "mimo_v2_flash",
    "vocab_size": 128, "hidden_size": 32, "intermediate_size": 64,
    "num_hidden_layers": 4, "num_attention_heads": 4,
    "num_key_value_heads": 2, "head_dim": 8, "v_head_dim": 8,
    "swa_num_attention_heads": 2, "swa_num_key_value_heads": 1,
    "swa_head_dim": 16, "swa_v_head_dim": 8,
    "hybrid_layer_pattern": [0, 1, 1, 0],
    "sliding_window": 8,
    "rope_theta": 5000000.0, "swa_rope_theta": 10000.0,
    "partial_rotary_factor": 0.5,
    "add_full_attention_sink_bias": False,
    "add_swa_attention_sink_bias": True,
    "n_routed_experts": 4, "num_experts_per_tok": 2,
    "moe_intermediate_size": 16, "scoring_func": "sigmoid",
    "n_group": 2, "topk_group": 2, "norm_topk_prob": True,
    "moe_layer_freq": [0, 1, 1, 1], "n_shared_experts": 1,
}


@pytest.mark.slow
def test_step3p5_forward_and_roundtrip():
    from automodel_tpu.checkpoint.hf_adapter import get_adapter
    from automodel_tpu.models.moe_lm import het_moe

    spec = get_model_spec(STEP35_HF)
    cfg = spec.config_from_hf(STEP35_HF, dtype=jnp.float32, remat_policy="none")
    assert cfg.layer_types == ("global", "sliding", "sliding", "global")
    assert cfg.mlp_kinds == ("dense", "moe", "dense", "moe")
    assert cfg.sliding_attn.num_heads == 2 and cfg.global_attn.num_heads == 4
    assert cfg.use_rope == (True, True, False, True)  # NoPE layer
    assert cfg.head_gate
    params = het_moe.init(cfg, jax.random.key(0))
    ids = jax.random.randint(jax.random.key(1), (2, 16), 0, 128)
    out, aux, stats = het_moe.forward(params, cfg, ids, return_stats=True)
    assert np.isfinite(np.asarray(out)).all()
    assert stats["tokens_per_expert"].shape == (2, 4)

    ad = get_adapter(spec.adapter_name, cfg, **spec.adapter_kwargs)
    sd = dict(ad.to_hf(params))
    assert sd["model.layers.1.moe.gate_proj.weight"].shape == (4, 16, 32)
    assert "model.layers.1.moe.router_bias" in sd
    assert "model.layers.1.share_expert.up_proj.weight" in sd
    assert "model.layers.0.self_attn.g_proj.weight" in sd
    assert sd["model.layers.1.self_attn.q_proj.weight"].shape == (2 * 8, 32)
    p2 = ad.from_hf(lambda k: np.asarray(sd[k]))
    o2, _, _ = het_moe.forward(
        jax.tree.map(jnp.asarray, p2), cfg, ids, return_stats=True
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(o2), atol=1e-5)


@pytest.mark.slow
def test_mimo_v2_flash_forward_and_roundtrip():
    from automodel_tpu.checkpoint.hf_adapter import get_adapter
    from automodel_tpu.models.moe_lm import het_moe

    spec = get_model_spec(MIMO_HF)
    cfg = spec.config_from_hf(MIMO_HF, dtype=jnp.float32, remat_policy="none")
    assert cfg.layer_types == ("global", "sliding", "sliding", "global")
    assert cfg.mlp_kinds == ("dense", "moe", "moe", "moe")
    assert cfg.sliding_attn.head_dim == 16 and cfg.sliding_attn.vd == 8
    assert cfg.sliding_attn.sinks and not cfg.global_attn.sinks
    params = het_moe.init(cfg, jax.random.key(0))
    # non-zero sinks so the path is exercised
    params["s_attn"]["sinks"] = 0.3 + 0.1 * jax.random.normal(
        jax.random.key(5), params["s_attn"]["sinks"].shape
    )
    ids = jax.random.randint(jax.random.key(1), (2, 16), 0, 128)
    out, aux, stats = het_moe.forward(params, cfg, ids, return_stats=True)
    assert np.isfinite(np.asarray(out)).all()
    assert stats["tokens_per_expert"].shape == (3, 4)

    ad = get_adapter(spec.adapter_name, cfg, **spec.adapter_kwargs)
    sd = dict(ad.to_hf(params))
    assert "model.layers.1.self_attn.attention_sink_bias" in sd
    assert "model.layers.0.self_attn.attention_sink_bias" not in sd
    assert "model.layers.1.mlp.gate.e_score_correction_bias" in sd
    assert "model.layers.1.mlp.shared_experts.down_proj.weight" in sd
    assert "model.layers.0.mlp.gate_proj.weight" in sd  # dense layer
    assert sd["model.layers.1.self_attn.k_proj.weight"].shape == (1 * 16, 32)
    p2 = ad.from_hf(lambda k: np.asarray(sd[k]))
    o2, _, _ = het_moe.forward(
        jax.tree.map(jnp.asarray, p2), cfg, ids, return_stats=True
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(o2), atol=1e-5)


@pytest.mark.recipe
def test_step3p5_recipe_trains(tmp_path):
    import json

    from automodel_tpu.cli.app import resolve_recipe_class
    from tests.unit.test_recipe import _smoke_cfg

    cfg = _smoke_cfg(tmp_path)
    cfg.set("model.hf_config", STEP35_HF)
    cfg.set("distributed", {"dp_shard": -1, "ep": 2})
    cfg.set("checkpoint.enabled", False)
    cfg.set("step_scheduler.max_steps", 3)
    r = resolve_recipe_class(cfg)(cfg)
    r.setup()
    assert r.is_moe
    r.run_train_validation_loop()
    recs = [json.loads(l) for l in open(tmp_path / "training.jsonl") if l.strip()]
    assert len(recs) == 3
    assert all(np.isfinite(x["loss"]) for x in recs)


MINISTRAL_HF = {
    "architectures": ["Ministral3BidirectionalModel"],
    "model_type": "ministral3",
    "vocab_size": 128, "hidden_size": 32, "intermediate_size": 64,
    "num_hidden_layers": 2, "num_attention_heads": 4,
    "num_key_value_heads": 2, "head_dim": 8,
    "rope_parameters": {"rope_theta": 1000000.0},
    "sliding_window": 16, "pooling": "avg",
}


def test_ministral3_and_bidirectional():
    spec = get_model_spec(MINISTRAL_HF)
    cfg = spec.config_from_hf(MINISTRAL_HF, dtype=jnp.float32, remat_policy="none")
    assert cfg.causal is False
    assert cfg.rope_theta == 1000000.0 and cfg.sliding_window == 16
    params = decoder.init(cfg, jax.random.key(0))
    ids = jax.random.randint(jax.random.key(1), (2, 12), 0, 128)
    h1 = decoder.forward(params, cfg, ids, return_hidden=True)
    # bidirectional: a LATE token change moves an EARLY hidden state
    ids2 = ids.at[0, -1].set((int(ids[0, -1]) + 1) % 128)
    h2 = decoder.forward(params, cfg, ids2, return_hidden=True)
    assert np.abs(np.asarray(h1[0, 0]) - np.asarray(h2[0, 0])).max() > 1e-7

    causal_hf = dict(MINISTRAL_HF, architectures=["Ministral3ForCausalLM"])
    cfg_c = get_model_spec(causal_hf).config_from_hf(
        causal_hf, dtype=jnp.float32, remat_policy="none"
    )
    assert cfg_c.causal is True


GLM_LITE_HF = {
    "architectures": ["Glm4MoeLiteForCausalLM"],
    "model_type": "glm4_moe_lite",
    "vocab_size": 128, "hidden_size": 32, "intermediate_size": 64,
    "num_hidden_layers": 2, "num_attention_heads": 4,
    "n_routed_experts": 4, "n_shared_experts": 1,
    "num_experts_per_tok": 2, "moe_intermediate_size": 16,
    "first_k_dense_replace": 1, "norm_topk_prob": True,
    "routed_scaling_factor": 1.0, "n_group": 2, "topk_group": 2,
    "kv_lora_rank": 16, "q_lora_rank": 12,
    "qk_nope_head_dim": 8, "qk_rope_head_dim": 8, "v_head_dim": 8,
}


def test_glm4_moe_lite_is_mla_moe():
    from automodel_tpu.models.moe_lm import decoder as moe_decoder

    spec = get_model_spec(GLM_LITE_HF)
    cfg = spec.config_from_hf(GLM_LITE_HF, dtype=jnp.float32, remat_policy="none")
    assert cfg.attention_type == "mla" and cfg.first_k_dense == 1
    assert cfg.moe.score_func == "sigmoid"
    params = moe_decoder.init(cfg, jax.random.key(0))
    ids = jax.random.randint(jax.random.key(1), (2, 8), 0, 128)
    logits, _ = moe_decoder.forward(params, cfg, ids)
    assert np.isfinite(np.asarray(logits)).all()


HY_MT2_HF = {
    "architectures": ["HyMT2ForCausalLM"],
    "model_type": "hy_mt2",
    "vocab_size": 128, "hidden_size": 32, "intermediate_size": 64,
    "num_hidden_layers": 2, "num_attention_heads": 4,
    "num_key_value_heads": 2, "head_dim": 8, "qk_norm": True,
    "num_experts": 4, "num_experts_per_tok": 2, "num_shared_experts": 1,
    "expert_hidden_dim": 16, "moe_intermediate_size": 16,
    "moe_router_use_sigmoid": True, "moe_router_enable_expert_bias": True,
    "first_k_dense_replace": 1, "rope_theta": 11158840.0,
}


@pytest.mark.slow
def test_hy_mt2_adapter_roundtrip():
    from automodel_tpu.checkpoint.hf_adapter import get_adapter
    from automodel_tpu.models.moe_lm import decoder as moe_decoder

    spec = get_model_spec(HY_MT2_HF)
    cfg = spec.config_from_hf(HY_MT2_HF, dtype=jnp.float32, remat_policy="none")
    assert cfg.qk_norm and cfg.first_k_dense == 1
    assert cfg.moe.score_func == "sigmoid"
    assert cfg.moe.gate_bias_update_speed > 0
    params = moe_decoder.init(cfg, jax.random.key(0))
    ad = get_adapter(spec.adapter_name, cfg, **spec.adapter_kwargs)
    sd = dict(ad.to_hf(params))
    # the Hy-MT2 on-disk layout (reference: hy_mt2/state_dict_adapter.py)
    assert "model.layers.1.mlp.router.gate.weight" in sd
    assert "model.layers.1.mlp.expert_bias" in sd
    assert "model.layers.1.mlp.shared_mlp.up_proj.weight" in sd
    assert not any(".mlp.gate.weight" in k for k in sd)
    p2 = ad.from_hf(lambda k: np.asarray(sd[k]))
    ids = jax.random.randint(jax.random.key(2), (2, 8), 0, 128)
    o1, _ = moe_decoder.forward(params, cfg, ids)
    o2, _ = moe_decoder.forward(jax.tree.map(jnp.asarray, p2), cfg, ids)
    np.testing.assert_allclose(np.asarray(o1), np.asarray(o2), atol=1e-5)


MISTRAL4_HF = {
    "architectures": ["Mistral4ForCausalLM"],
    "model_type": "mistral4",
    "vocab_size": 128, "hidden_size": 32, "intermediate_size": 64,
    "num_hidden_layers": 2, "num_attention_heads": 4,
    "n_routed_experts": 4, "n_shared_experts": 1,
    "num_experts_per_tok": 2, "moe_intermediate_size": 16,
    "first_k_dense_replace": 1, "norm_topk_prob": True,
    "routed_scaling_factor": 1.0,
    "kv_lora_rank": 16, "q_lora_rank": 12,
    "qk_nope_head_dim": 8, "qk_rope_head_dim": 8, "v_head_dim": 8,
    "rope_parameters": {
        "rope_theta": 10000.0, "llama_4_scaling_beta": 0.1,
        "original_max_position_embeddings": 8,
    },
}


@pytest.mark.slow
def test_mistral4_llama4_qpe_scaling():
    """Positions past orig_max get the llama4 log scaling on q_pe — the
    forward must differ from the unscaled config exactly there."""
    import dataclasses

    from automodel_tpu.models.moe_lm import decoder as moe_decoder

    spec = get_model_spec(MISTRAL4_HF)
    cfg = spec.config_from_hf(MISTRAL4_HF, dtype=jnp.float32, remat_policy="none")
    assert cfg.mla_qpe_scaling_beta == 0.1
    assert cfg.mla_qpe_scaling_orig_max == 8
    params = moe_decoder.init(cfg, jax.random.key(0))
    ids = jax.random.randint(jax.random.key(1), (1, 16), 0, 128)
    l_scaled, _ = moe_decoder.forward(params, cfg, ids)
    cfg_off = dataclasses.replace(cfg, mla_qpe_scaling_beta=None)
    l_plain, _ = moe_decoder.forward(params, cfg_off, ids)
    d = np.abs(np.asarray(l_scaled) - np.asarray(l_plain)).max(axis=-1)[0]
    # positions 0..7: floor(pos/8)=0 → scale 1 → identical
    assert d[:8].max() < 1e-6, d[:8]
    # positions 8..: scale > 1 → outputs differ
    assert d[8:].max() > 1e-6, d[8:]
