"""NanoGPT bin-shard reader + SQuAD/HellaSwag preset tests."""

import json

import numpy as np
import pytest

from automodel_tpu.datasets.nanogpt import (
    LEGACY_MAGIC,
    HEADER_INTS,
    NanogptBinDatasetConfig,
    write_bin_shard,
)
from automodel_tpu.datasets.presets import (
    HellaSwagDatasetConfig,
    SquadDatasetConfig,
)


class FakeTok:
    bos_token_id = 1
    eos_token_id = 2
    pad_token_id = 0

    def __call__(self, text, add_special_tokens=False):
        # 1 token per character, offset out of the specials range
        return {"input_ids": [3 + (ord(c) % 50) for c in text]}


def test_nanogpt_roundtrip_and_chunking(tmp_path):
    toks = np.arange(1000, dtype=np.uint16)
    write_bin_shard(toks, str(tmp_path / "s0.bin"))
    write_bin_shard(toks + 1000, str(tmp_path / "s1.bin"))

    ds = NanogptBinDatasetConfig(
        path=str(tmp_path / "s*.bin"), seq_len=100, shuffle_seed=None
    ).build()
    # 9 full windows of 101 per shard ((1000-1)//100 = 9)
    assert len(ds) == 18
    s = ds[0]
    np.testing.assert_array_equal(s["input_ids"], np.arange(100))
    np.testing.assert_array_equal(s["labels"], np.arange(1, 101))
    s = ds[9]  # first window of shard 1
    assert s["input_ids"][0] == 1000


def test_nanogpt_shuffle_is_seeded(tmp_path):
    write_bin_shard(np.arange(5000, dtype=np.uint16), str(tmp_path / "a.bin"))
    d1 = NanogptBinDatasetConfig(path=str(tmp_path / "a.bin"), seq_len=64, shuffle_seed=3).build()
    d2 = NanogptBinDatasetConfig(path=str(tmp_path / "a.bin"), seq_len=64, shuffle_seed=3).build()
    d3 = NanogptBinDatasetConfig(path=str(tmp_path / "a.bin"), seq_len=64, shuffle_seed=4).build()
    np.testing.assert_array_equal(d1.index, d2.index)
    assert not np.array_equal(d1.index, d3.index)
    # all windows covered exactly once
    assert sorted(d1.index[:, 1].tolist()) == sorted(d3.index[:, 1].tolist())


def test_nanogpt_legacy_header_and_uint32(tmp_path):
    # legacy: magic 20240520, no itemsize field (uint16 implied)
    toks = np.arange(500, dtype=np.uint16)
    header = np.zeros(HEADER_INTS, np.int32)
    header[0], header[1], header[2] = LEGACY_MAGIC, 1, toks.size
    with open(tmp_path / "legacy.bin", "wb") as f:
        f.write(header.tobytes())
        f.write(toks.tobytes())
    ds = NanogptBinDatasetConfig(path=str(tmp_path / "legacy.bin"), seq_len=50).build()
    assert len(ds) > 0 and ds[0]["input_ids"].dtype == np.int32

    big = (np.arange(500, dtype=np.uint32) + 70000)  # needs uint32
    write_bin_shard(big, str(tmp_path / "u32.bin"))
    ds32 = NanogptBinDatasetConfig(path=str(tmp_path / "u32.bin"), seq_len=50, shuffle_seed=None).build()
    assert int(ds32[0]["input_ids"][0]) == 70000

    with pytest.raises(ValueError, match="bad magic"):
        bad = tmp_path / "bad.bin"
        bad.write_bytes(b"\x00" * 2048)
        NanogptBinDatasetConfig(path=str(bad), seq_len=10).build()


def test_squad_preset_masks_prompt(tmp_path):
    rows = [{
        "context": "Paris is in France.",
        "question": "Where is Paris?",
        "answers": {"text": ["France"], "answer_start": [0]},
    }]
    p = tmp_path / "squad.jsonl"
    p.write_text("\n".join(json.dumps(r) for r in rows))
    ds = SquadDatasetConfig(path_or_dataset=str(p), seq_len=96).build(FakeTok())
    s = ds[0]
    assert s["input_ids"].shape == (96,)
    sup = s["labels"] != -100
    # supervision exists and starts only after the prompt region
    assert sup.any()
    n_prompt = len(FakeTok()("Context: Paris is in France.\nQuestion: Where is Paris?\nAnswer:")["input_ids"])
    assert not sup[: n_prompt - 2].any()


def test_hellaswag_preset_picks_labeled_ending(tmp_path):
    rows = [{"ctx": "A man sits down", "endings": ["x", "and reads.", "z"], "label": 1}]
    p = tmp_path / "hs.jsonl"
    p.write_text(json.dumps(rows[0]))
    ds = HellaSwagDatasetConfig(path_or_dataset=str(p), seq_len=64).build(FakeTok())
    s = ds[0]
    n_ans = len(FakeTok()(" and reads.")["input_ids"])
    assert int((s["labels"] != -100).sum()) >= n_ans


def test_nanogpt_bos_alignment(tmp_path):
    toks = np.zeros(1000, np.uint16)
    bos_positions = [0, 150, 160, 400, 990]
    for p in bos_positions:
        toks[p] = 7
    write_bin_shard(toks, str(tmp_path / "bos.bin"))
    ds = NanogptBinDatasetConfig(
        path=str(tmp_path / "bos.bin"), seq_len=100, shuffle_seed=None,
        bos_token_id=7,
    ).build()
    starts = sorted(ds.index[:, 1].tolist())
    # greedy non-overlap: 0 taken, 150 taken (>=100), 160 skipped, 400 taken;
    # 990 has no full window
    assert starts == [0, 150, 400]
    assert all(ds[i]["input_ids"][0] == 7 for i in range(len(ds)))


def test_squad_official_nested_format(tmp_path):
    official = {"data": [{
        "title": "t",
        "paragraphs": [{
            "context": "Rome is in Italy.",
            "qas": [
                {"question": "Where is Rome?", "answers": [{"text": "Italy", "answer_start": 0}]},
                {"question": "What is Rome?", "answers": [{"text": "a city", "answer_start": 0}]},
            ],
        }],
    }]}
    p = tmp_path / "train.json"
    p.write_text(json.dumps(official))
    ds = SquadDatasetConfig(path_or_dataset=str(p), seq_len=96).build(FakeTok())
    assert len(ds) == 2
    s = ds[0]
    assert (s["labels"] != -100).sum() > 0
    # the answer text is actually tokenized into the sequence (not empty)
    n_ans = len(FakeTok()("Italy")["input_ids"])
    assert (s["labels"] != -100).sum() >= n_ans
