"""Seq-classification and retrieval recipe tiers."""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.recipe

from automodel_tpu.cli.app import resolve_recipe_class
from automodel_tpu.config import ConfigNode
from automodel_tpu.loss.infonce import info_nce_loss, mean_pool


def test_infonce_perfect_alignment_low_loss():
    e = jax.random.normal(jax.random.key(0), (8, 16))
    loss_same, n = info_nce_loss(e, e, temperature=0.05)
    loss_rand, _ = info_nce_loss(
        e, jax.random.normal(jax.random.key(1), (8, 16)), temperature=0.05
    )
    assert n == 8
    assert float(loss_same) / 8 < 0.01
    assert float(loss_rand) > float(loss_same)


def test_mean_pool_masks():
    h = jnp.ones((1, 4, 2)) * jnp.asarray([1.0, 2.0, 3.0, 100.0])[None, :, None]
    mask = jnp.asarray([[1, 1, 1, 0]])
    np.testing.assert_allclose(np.asarray(mean_pool(h, mask)), 2.0)


def _base(tmp_path, recipe, model_extra=None):
    hf = {
        "architectures": ["LlamaForCausalLM"],
        "vocab_size": 512, "hidden_size": 32, "intermediate_size": 64,
        "num_hidden_layers": 2, "num_attention_heads": 4, "num_key_value_heads": 2,
    }
    return ConfigNode({
        "seed": 3, "recipe": recipe, "run_dir": str(tmp_path), "auto_resume": False,
        "model": {"hf_config": hf, "dtype": "float32", "remat_policy": "none"},
        "distributed": {"dp_shard": -1},
        "dataloader": {"microbatch_size": 8, "grad_acc_steps": 1},
        "optimizer": {"name": "adamw", "lr": 1e-3, "weight_decay": 0.0},
        "lr_scheduler": {"style": "constant", "warmup_steps": 0},
        "step_scheduler": {"max_steps": 8, "ckpt_every_steps": 1000},
        "checkpoint": {"enabled": False},
        "loss": {"chunk_size": 32},
    })


def test_seq_cls_recipe_learns(tmp_path):
    cfg = _base(tmp_path, "llm_seq_cls")
    cfg.set("seq_cls", {"num_labels": 4})
    cfg.set("dataset", {
        "_target_": "automodel_tpu.datasets.mock.MockSeqClsDatasetConfig",
        "num_samples": 64, "seq_len": 32, "vocab_size": 512, "num_labels": 4,
    })
    r = resolve_recipe_class(cfg)(cfg)
    assert type(r).__name__ == "TrainSeqClsRecipe"
    r.setup()
    r.run_train_validation_loop()
    recs = [json.loads(l) for l in open(tmp_path / "training.jsonl")]
    assert len(recs) == 8
    assert all(np.isfinite(x["loss"]) for x in recs)
    # accuracy metric present and sane
    assert 0 <= recs[-1]["num_correct"] <= 8


def test_bi_encoder_recipe_learns(tmp_path):
    cfg = _base(tmp_path, "retrieval_bi_encoder")
    cfg.set("dataset", {
        "_target_": "automodel_tpu.datasets.mock.MockRetrievalDatasetConfig",
        "num_samples": 64, "seq_len": 16, "vocab_size": 512,
    })
    cfg.set("retrieval", {"temperature": 0.05})
    cfg.set("step_scheduler.max_steps", 12)
    cfg.set("step_scheduler.num_epochs", 4)
    r = resolve_recipe_class(cfg)(cfg)
    r.setup()
    assert not r.model_cfg.causal  # backbone flipped to bidirectional
    r.run_train_validation_loop()
    recs = [json.loads(l) for l in open(tmp_path / "training.jsonl")]
    assert recs[-1]["loss"] < recs[0]["loss"]  # in-batch contrastive learns


def test_cross_encoder_recipe_learns(tmp_path):
    cfg = _base(tmp_path, "retrieval_cross_encoder")
    cfg.set("dataset", {
        "_target_": "automodel_tpu.datasets.mock.MockRerankDatasetConfig",
        "num_samples": 64, "seq_len": 16, "group_size": 4, "vocab_size": 512,
    })
    cfg.set("step_scheduler.max_steps", 12)
    cfg.set("step_scheduler.num_epochs", 4)
    r = resolve_recipe_class(cfg)(cfg)
    assert type(r).__name__ == "TrainCrossEncoderRecipe"
    r.setup()
    r.run_train_validation_loop()
    recs = [json.loads(l) for l in open(tmp_path / "training.jsonl")]
    # reranking accuracy (positive ranked first) improves over chance (0.25)
    assert recs[-1]["num_correct"] / 8 > 0.5
    assert recs[-1]["loss"] < recs[0]["loss"]


def test_length_grouped_order():
    from automodel_tpu.datasets.loader import length_grouped_order

    lengths = np.random.default_rng(0).integers(1, 500, 512)
    order = length_grouped_order(lengths, microbatch_size=8, seed=1, epoch=0)
    assert sorted(order.tolist()) == list(range(512))
    # microbatches have low length spread vs random order
    def spread(o):
        ls = lengths[o].reshape(-1, 8)
        return float((ls.max(1) - ls.min(1)).mean())

    assert spread(order) < spread(np.arange(512)) * 0.5
    # different epochs differ
    assert not np.array_equal(order, length_grouped_order(lengths, 8, 1, 1))


def test_skip_nonfinite_updates():
    import jax
    import jax.numpy as jnp

    from automodel_tpu.optim import OptimizerConfig
    from automodel_tpu.training import (
        TrainStepConfig,
        init_train_state,
        make_train_step,
    )

    def loss_fn(p, b, rng):
        # boom multiplies the PARAM-dependent term so gradients blow up too
        scale = jnp.where(b["boom"][0] > 0, jnp.inf, 1.0)
        return jnp.sum(p["w"] * b["x"]) * scale, jnp.float32(1.0)

    tx = OptimizerConfig(lr=0.1, weight_decay=0.0).build()
    params = {"w": jnp.ones((4,))}
    state = init_train_state(params, tx)
    step = jax.jit(make_train_step(loss_fn, tx, None, TrainStepConfig(
        max_grad_norm=None, skip_nonfinite_updates=True)))
    good = {"x": jnp.ones((1, 1, 4)), "boom": jnp.zeros((1, 1))}
    bad = {"x": jnp.ones((1, 1, 4)), "boom": jnp.ones((1, 1))}
    s1, m1 = step(state, good, jax.random.key(0))
    assert m1["skipped_nonfinite"] == 0.0
    s2, m2 = step(s1, bad, jax.random.key(0))
    assert m2["skipped_nonfinite"] == 1.0
    np.testing.assert_array_equal(np.asarray(s2.params["w"]), np.asarray(s1.params["w"]))


def test_distill_bi_encoder_matches_teacher(tmp_path):
    """Distillation (reference: recipes/retrieval/distill_bi_encoder.py):
    KL between in-batch similarity rows decreases as the student learns."""
    cfg = _base(tmp_path, "retrieval_distill_bi_encoder")
    cfg.set("dataset", {
        "_target_": "automodel_tpu.datasets.mock.MockRetrievalDatasetConfig",
        "num_samples": 64, "seq_len": 16, "vocab_size": 512,
    })
    cfg.set("teacher_model", {
        "hf_config": {
            "architectures": ["LlamaForCausalLM"],
            "vocab_size": 512, "hidden_size": 32, "intermediate_size": 64,
            "num_hidden_layers": 2, "num_attention_heads": 4,
            "num_key_value_heads": 2,
        },
        "dtype": "float32",
    })
    cfg.set("distill", {"weight": 1.0, "teacher_temperature": 0.05})
    cfg.set("step_scheduler.max_steps", 12)
    cfg.set("step_scheduler.num_epochs", 4)
    r = resolve_recipe_class(cfg)(cfg)
    r.setup()
    assert not r.teacher_cfg.causal
    r.run_train_validation_loop()
    recs = [json.loads(l) for l in open(tmp_path / "training.jsonl")]
    assert recs[-1]["loss"] < recs[0]["loss"]


def test_mine_hard_negatives_logic(tmp_path):
    """Margin + top-k + own-positive exclusion with synthetic embeddings."""
    import numpy as np

    from automodel_tpu.config import ConfigNode
    from automodel_tpu.recipes.retrieval.mine_hard_negatives import (
        MineHardNegativesRecipe,
    )

    qa = tmp_path / "qa.jsonl"
    corpus = tmp_path / "corpus.jsonl"
    out = tmp_path / "out.jsonl"
    docs = [f"doc{i}" for i in range(8)]
    qa.write_text("\n".join(
        json.dumps({"query": f"q{i}", "pos_doc": docs[i]}) for i in range(3)
    ))
    corpus.write_text("\n".join(json.dumps({"doc": d}) for d in docs))

    r = MineHardNegativesRecipe(ConfigNode({
        "mining": {
            "train_qa_file_path": str(qa),
            "corpus_file_path": str(corpus),
            "train_file_output_path": str(out),
            "hard_negatives_to_mine": 2,
            "hard_neg_margin": 0.99,
            "hard_neg_margin_type": "perc",
            "corpus_chunk_size": 3,
        },
    }))
    r.m = r.cfg.get("mining")

    # deterministic embeddings: query i ≡ doc i; similarity = dot
    emb = np.eye(8, 4, dtype=np.float32)
    emb = emb + 0.1 * np.arange(8)[:, None] * np.ones((8, 4), np.float32)
    emb = emb / np.linalg.norm(emb, axis=-1, keepdims=True)
    table = {f"q{i}": emb[i] for i in range(3)}
    table.update({d: emb[i] for i, d in enumerate(docs)})

    r._encode = lambda texts, prefix, max_len, bs: np.stack(
        [table[t] for t in texts]
    )
    r.run()
    rows = [json.loads(l) for l in open(out)]
    assert len(rows) == 3
    for i, row in enumerate(rows):
        assert len(row["neg_docs"]) <= 2
        assert docs[i] not in row["neg_docs"]  # own positive excluded
        # margin: every mined negative scores below 0.99 * positive score
        pos = float(table[f"q{i}"] @ table[docs[i]])
        for nd in row["neg_docs"]:
            assert float(table[f"q{i}"] @ table[nd]) < 0.99 * pos
