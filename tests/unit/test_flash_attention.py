"""Flash-attention kernel parity vs the XLA oracle (interpret mode on CPU)."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from automodel_tpu.ops.attention import make_attention_mask, xla_attention
from automodel_tpu.ops.pallas.flash_attention import BlockSizes, flash_attention

SMALL_BLOCKS = BlockSizes(block_q=128, block_kv=128, block_q_dq=128, block_kv_dkv=128)


def _rand_qkv(key, B=1, S=256, Hq=4, Hkv=2, D=128, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (B, S, Hq, D), dtype)
    k = jax.random.normal(kk, (B, S, Hkv, D), dtype)
    v = jax.random.normal(kv, (B, S, Hkv, D), dtype)
    return q, k, v


def _oracle(q, k, v, **kw):
    mask = make_attention_mask(
        q.shape[1], k.shape[1],
        causal=kw.get("causal", True),
        q_segment_ids=kw.get("segment_ids"),
        kv_segment_ids=kw.get("segment_ids"),
        q_positions=kw.get("positions"),
        kv_positions=kw.get("positions"),
        sliding_window=kw.get("sliding_window"),
    )
    return xla_attention(
        q, k, v, mask=mask,
        scale=kw.get("scale"), logits_soft_cap=kw.get("logits_soft_cap"),
    )


CASES = {
    "causal": {},
    "noncausal": {"causal": False},
    "gqa8": {"Hq": 8, "Hkv": 2},
    "mha": {"Hq": 2, "Hkv": 2},
    "window": {"sliding_window": 100},
    "softcap": {"logits_soft_cap": 20.0},
    "scale": {"scale": 0.05},
}


@pytest.mark.parametrize("name", sorted(CASES))
def test_fwd_parity(name):
    kw = dict(CASES[name])
    shape_kw = {k: kw.pop(k) for k in ("Hq", "Hkv") if k in kw}
    q, k, v = _rand_qkv(jax.random.key(0), **shape_kw)
    out = flash_attention(q, k, v, block_sizes=SMALL_BLOCKS, **kw)
    ref = _oracle(q, k, v, **kw)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4)


def test_fwd_packed_segments():
    q, k, v = _rand_qkv(jax.random.key(1), S=256)
    seg = jnp.concatenate(
        [jnp.zeros((1, 100), jnp.int32), jnp.ones((1, 156), jnp.int32)], axis=1
    )
    pos = jnp.concatenate(
        [jnp.arange(100)[None], jnp.arange(156)[None]], axis=1
    ).astype(jnp.int32)
    out = flash_attention(q, k, v, segment_ids=seg, positions=pos, block_sizes=SMALL_BLOCKS)
    ref = _oracle(q, k, v, segment_ids=seg, positions=pos)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("name", ["causal", "gqa8", "window", "softcap"])
def test_bwd_parity(name):
    kw = dict(CASES[name])
    shape_kw = {k: kw.pop(k) for k in ("Hq", "Hkv") if k in kw}
    q, k, v = _rand_qkv(jax.random.key(2), S=256, **shape_kw)

    def f_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, block_sizes=SMALL_BLOCKS, **kw) ** 2)

    def f_ref(q, k, v):
        return jnp.sum(_oracle(q, k, v, **kw) ** 2)

    g1 = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, n in zip(g1, g2, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-3, atol=5e-3, err_msg=f"d{n}"
        )


def test_bwd_packed_segments():
    q, k, v = _rand_qkv(jax.random.key(3), S=256)
    seg = jnp.concatenate(
        [jnp.zeros((1, 128), jnp.int32), jnp.ones((1, 128), jnp.int32)], axis=1
    )

    def f_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, segment_ids=seg, block_sizes=SMALL_BLOCKS) ** 2)

    def f_ref(q, k, v):
        return jnp.sum(_oracle(q, k, v, segment_ids=seg) ** 2)

    g1 = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=5e-3, atol=5e-3)


def test_unsupported_shapes_raise():
    q = jnp.zeros((1, 100, 4, 64))  # seq not 128-divisible, head_dim 64
    with pytest.raises(NotImplementedError):
        flash_attention(q, q, q)
