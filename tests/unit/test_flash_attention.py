"""Flash-attention kernel parity vs the XLA oracle (interpret mode on CPU)."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from automodel_tpu.ops.attention import make_attention_mask, xla_attention
from automodel_tpu.ops.pallas.flash_attention import BlockSizes, flash_attention

SMALL_BLOCKS = BlockSizes(block_q=128, block_kv=128, block_q_dq=128, block_kv_dkv=128)


def _rand_qkv(key, B=1, S=256, Hq=4, Hkv=2, D=128, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (B, S, Hq, D), dtype)
    k = jax.random.normal(kk, (B, S, Hkv, D), dtype)
    v = jax.random.normal(kv, (B, S, Hkv, D), dtype)
    return q, k, v


def _oracle(q, k, v, **kw):
    mask = make_attention_mask(
        q.shape[1], k.shape[1],
        causal=kw.get("causal", True),
        q_segment_ids=kw.get("segment_ids"),
        kv_segment_ids=kw.get("segment_ids"),
        q_positions=kw.get("positions"),
        kv_positions=kw.get("positions"),
        sliding_window=kw.get("sliding_window"),
    )
    return xla_attention(
        q, k, v, mask=mask,
        scale=kw.get("scale"), logits_soft_cap=kw.get("logits_soft_cap"),
    )


CASES = {
    "causal": {},
    "noncausal": {"causal": False},
    "gqa8": {"Hq": 8, "Hkv": 2},
    "mha": {"Hq": 2, "Hkv": 2},
    "window": {"sliding_window": 100},
    "noncausal_window": {"causal": False, "sliding_window": 100},
    "softcap": {"logits_soft_cap": 20.0},
    "scale": {"scale": 0.05},
}


@pytest.mark.slow
@pytest.mark.parametrize("name", sorted(CASES))
def test_fwd_parity(name):
    kw = dict(CASES[name])
    shape_kw = {k: kw.pop(k) for k in ("Hq", "Hkv") if k in kw}
    q, k, v = _rand_qkv(jax.random.key(0), **shape_kw)
    out = flash_attention(q, k, v, block_sizes=SMALL_BLOCKS, **kw)
    ref = _oracle(q, k, v, **kw)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4)


def test_fwd_packed_segments():
    q, k, v = _rand_qkv(jax.random.key(1), S=256)
    seg = jnp.concatenate(
        [jnp.zeros((1, 100), jnp.int32), jnp.ones((1, 156), jnp.int32)], axis=1
    )
    pos = jnp.concatenate(
        [jnp.arange(100)[None], jnp.arange(156)[None]], axis=1
    ).astype(jnp.int32)
    out = flash_attention(q, k, v, segment_ids=seg, positions=pos, block_sizes=SMALL_BLOCKS)
    ref = _oracle(q, k, v, segment_ids=seg, positions=pos)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4)


@pytest.mark.slow
def test_fwd_noncausal_window_block_skip():
    """S=512 with window 100 and 128-blocks: kv blocks fully outside the
    two-sided window are skipped by _run_predicate; parity proves no valid
    block is dropped."""
    q, k, v = _rand_qkv(jax.random.key(7), S=512)
    kw = {"causal": False, "sliding_window": 100}
    out = flash_attention(q, k, v, block_sizes=SMALL_BLOCKS, **kw)
    ref = _oracle(q, k, v, **kw)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4)


@pytest.mark.slow
@pytest.mark.parametrize("name", ["causal", "gqa8", "window", "noncausal_window", "softcap"])
def test_bwd_parity(name):
    kw = dict(CASES[name])
    shape_kw = {k: kw.pop(k) for k in ("Hq", "Hkv") if k in kw}
    q, k, v = _rand_qkv(jax.random.key(2), S=256, **shape_kw)

    def f_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, block_sizes=SMALL_BLOCKS, **kw) ** 2)

    def f_ref(q, k, v):
        return jnp.sum(_oracle(q, k, v, **kw) ** 2)

    g1 = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, n in zip(g1, g2, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-3, atol=5e-3, err_msg=f"d{n}"
        )


@pytest.mark.slow
def test_bwd_packed_segments():
    q, k, v = _rand_qkv(jax.random.key(3), S=256)
    seg = jnp.concatenate(
        [jnp.zeros((1, 128), jnp.int32), jnp.ones((1, 128), jnp.int32)], axis=1
    )

    def f_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, segment_ids=seg, block_sizes=SMALL_BLOCKS) ** 2)

    def f_ref(q, k, v):
        return jnp.sum(_oracle(q, k, v, segment_ids=seg) ** 2)

    g1 = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=5e-3, atol=5e-3)


def test_unsupported_shapes_raise():
    q = jnp.zeros((1, 100, 4, 64))  # seq not 128-divisible
    with pytest.raises(NotImplementedError):
        flash_attention(q, q, q)


@pytest.mark.slow
@pytest.mark.parametrize("D", [64, 96])
def test_narrow_head_dim_padded(D):
    """head_dim 64/96 (gpt-oss, qwen2-0.5B class) runs via lane padding."""
    q, k, v = _rand_qkv(jax.random.key(4), S=256, D=D)
    out = flash_attention(q, k, v, block_sizes=SMALL_BLOCKS)
    ref = _oracle(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4)

    g1 = jax.grad(lambda *a: jnp.sum(flash_attention(*a, block_sizes=SMALL_BLOCKS) ** 2),
                  argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(lambda *a: jnp.sum(_oracle(*a) ** 2), argnums=(0, 1, 2))(q, k, v)
    for a, b, n in zip(g1, g2, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-3, atol=5e-3, err_msg=f"d{n}"
        )


@pytest.mark.slow
def test_mla_shaped_heads():
    """MLA: q/k head_dim (192) differs from v head_dim (128)."""
    key = jax.random.key(5)
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (1, 256, 4, 192))
    k = jax.random.normal(kk, (1, 256, 4, 192))
    v = jax.random.normal(kv, (1, 256, 4, 128))
    out = flash_attention(q, k, v, block_sizes=SMALL_BLOCKS)
    ref = _oracle(q, k, v)
    assert out.shape == (1, 256, 4, 128)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4)

    g1 = jax.grad(lambda *a: jnp.sum(flash_attention(*a, block_sizes=SMALL_BLOCKS) ** 2),
                  argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(lambda *a: jnp.sum(_oracle(*a) ** 2), argnums=(0, 1, 2))(q, k, v)
    for a, b, n in zip(g1, g2, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-3, atol=5e-3, err_msg=f"d{n}"
        )


@pytest.mark.slow
def test_sinks_parity():
    """gpt-oss attention sinks: fwd/bwd parity incl. the sink gradient."""
    q, k, v = _rand_qkv(jax.random.key(6), S=256, Hq=4, Hkv=2)
    sinks = jax.random.normal(jax.random.key(7), (4,))

    def f_flash(q, k, v, s):
        return jnp.sum(
            flash_attention(q, k, v, sinks=s, block_sizes=SMALL_BLOCKS) ** 2
        )

    def f_ref(q, k, v, s):
        mask = make_attention_mask(q.shape[1], k.shape[1], causal=True)
        return jnp.sum(xla_attention(q, k, v, mask=mask, sinks=s) ** 2)

    np.testing.assert_allclose(
        np.asarray(flash_attention(q, k, v, sinks=sinks, block_sizes=SMALL_BLOCKS)),
        np.asarray(xla_attention(
            q, k, v,
            mask=make_attention_mask(q.shape[1], k.shape[1], causal=True),
            sinks=sinks,
        )),
        rtol=2e-4, atol=2e-4,
    )
    g1 = jax.grad(f_flash, argnums=(0, 1, 2, 3))(q, k, v, sinks)
    g2 = jax.grad(f_ref, argnums=(0, 1, 2, 3))(q, k, v, sinks)
    for a, b, n in zip(g1, g2, ("q", "k", "v", "sinks")):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-3, atol=5e-3, err_msg=f"d{n}"
        )


def test_traced_sliding_window():
    """A traced (scan-carried) window matches the static-window kernel."""
    q, k, v = _rand_qkv(jax.random.key(8), S=256)
    ref = flash_attention(q, k, v, sliding_window=100, block_sizes=SMALL_BLOCKS)

    @jax.jit
    def run(w):
        return flash_attention(q, k, v, sliding_window=w, block_sizes=SMALL_BLOCKS)

    out = run(jnp.int32(100))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4)


@pytest.mark.slow
def test_position_causal_asymmetric_kv():
    """Ring-step mode: kv carries its own global positions/segments."""
    B, S, H, D = 1, 128, 2, 128
    kq, kk, kv = jax.random.split(jax.random.key(9), 3)
    q = jax.random.normal(kq, (B, S, H, D))
    k = jax.random.normal(kk, (B, S, H, D))
    v = jax.random.normal(kv, (B, S, H, D))
    # q holds global tokens [128..256), visiting kv block holds [0..128)
    qpos = jnp.arange(S, dtype=jnp.int32)[None] + S
    kpos = jnp.arange(S, dtype=jnp.int32)[None]
    out, lse = flash_attention(
        q, k, v, positions=qpos, kv_positions=kpos,
        block_sizes=SMALL_BLOCKS, return_lse=True,
    )
    # every kv position precedes every q position → dense (non-causal) scores
    ref = _oracle(q, k, v, causal=False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4)
    assert lse.shape == (B, H, S)

    # reversed: q precedes all kv → fully masked, zero output, -inf-like lse
    out2, lse2 = flash_attention(
        q, k, v, positions=kpos, kv_positions=qpos + 1,
        block_sizes=SMALL_BLOCKS, return_lse=True,
    )
    np.testing.assert_allclose(np.asarray(out2), 0.0, atol=1e-6)
    assert bool(jnp.all(lse2 < -1e30))


@pytest.mark.slow
def test_return_lse_differentiable():
    """lse cotangents fold into the kernel backward (ring merge needs this)."""
    q, k, v = _rand_qkv(jax.random.key(10), S=128)

    def f_flash(q, k, v):
        out, lse = flash_attention(q, k, v, block_sizes=SMALL_BLOCKS, return_lse=True)
        return jnp.sum(out ** 2) + jnp.sum(jnp.sin(lse))

    def f_ref(q, k, v):
        mask = make_attention_mask(q.shape[1], k.shape[1], causal=True)
        B, S, Hq, D = q.shape
        G = Hq // k.shape[2]
        qg = q.reshape(B, S, k.shape[2], G, D)
        s = jnp.einsum("bskgd,btkd->bkgst", qg, k) * (D ** -0.5)
        s = jnp.where(mask[None, None, None], s, -jnp.inf)
        lse = jax.scipy.special.logsumexp(s, axis=-1)  # (B,Hkv,G,S)
        lse = lse.reshape(B, Hq, S)
        out = xla_attention(q, k, v, mask=mask)
        return jnp.sum(out ** 2) + jnp.sum(jnp.sin(lse))

    np.testing.assert_allclose(
        float(f_flash(q, k, v)), float(f_ref(q, k, v)), rtol=1e-4
    )
    g1 = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, n in zip(g1, g2, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-3, atol=5e-3, err_msg=f"d{n}"
        )
