"""Flow-matching + DiT tests (reference: components/flow_matching/
pipeline.py interpolation/σ-sampling semantics, recipes/diffusion/train.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from automodel_tpu.diffusion import (
    euler_sample,
    flow_matching_loss,
    interpolate,
    sample_sigmas,
    time_shift,
)
from automodel_tpu.models.diffusion import dit
from automodel_tpu.models.diffusion.dit import DiTConfig

import pytest

pytestmark = pytest.mark.recipe

CFG = DiTConfig(
    input_size=8, patch_size=2, in_channels=2, hidden_size=64,
    num_layers=2, num_heads=4, num_classes=3, remat_policy="none",
)


def test_sigma_sampling_and_shift():
    s = sample_sigmas(jax.random.key(0), 4096, scheme="uniform")
    assert 0.0 <= float(s.min()) and float(s.max()) <= 1.0
    np.testing.assert_allclose(float(s.mean()), 0.5, atol=0.03)
    ln = sample_sigmas(jax.random.key(1), 4096, scheme="logit_normal")
    np.testing.assert_allclose(float(ln.mean()), 0.5, atol=0.03)

    # shift=3 pushes mass toward 1; endpoints fixed
    sig = jnp.asarray([0.0, 0.5, 1.0])
    sh = time_shift(sig, 3.0)
    np.testing.assert_allclose(np.asarray(sh), [0.0, 0.75, 1.0], rtol=1e-6)


def test_interpolation_endpoints():
    x0 = jnp.ones((2, 4, 4, 1))
    x1 = jnp.zeros((2, 4, 4, 1))
    np.testing.assert_allclose(
        np.asarray(interpolate(x0, x1, jnp.asarray([0.0, 1.0]))[0]), 1.0
    )
    np.testing.assert_allclose(
        np.asarray(interpolate(x0, x1, jnp.asarray([0.0, 1.0]))[1]), 0.0
    )


def test_dit_zero_init_outputs_zero():
    """adaLN-zero: gates and the final head are zero-init, so the untrained
    model predicts exactly zero velocity (DiT's identity start)."""
    params = dit.init(CFG, jax.random.key(0))
    x = jax.random.normal(jax.random.key(1), (2, 8, 8, 2))
    v = dit.forward(params, CFG, x, jnp.asarray([0.3, 0.9]))
    assert v.shape == x.shape
    np.testing.assert_allclose(np.asarray(v), 0.0, atol=1e-6)


def test_dit_conditioning_changes_output():
    params = dit.init(CFG, jax.random.key(0))
    # break the zero-init so conditioning has a path to the output
    params["final"]["out"]["kernel"] = 0.1 * jax.random.normal(
        jax.random.key(5), params["final"]["out"]["kernel"].shape
    )
    params["final"]["mod"]["kernel"] = 0.1 * jax.random.normal(
        jax.random.key(6), params["final"]["mod"]["kernel"].shape
    )
    x = jax.random.normal(jax.random.key(1), (2, 8, 8, 2))
    sig = jnp.asarray([0.5, 0.5])
    v0 = dit.forward(params, CFG, x, sig, class_labels=jnp.asarray([0, 0]))
    v1 = dit.forward(params, CFG, x, sig, class_labels=jnp.asarray([1, 1]))
    vs = dit.forward(params, CFG, x, jnp.asarray([0.1, 0.1]), class_labels=jnp.asarray([0, 0]))
    assert float(jnp.abs(v0 - v1).max()) > 1e-7   # class matters
    assert float(jnp.abs(v0 - vs).max()) > 1e-7   # sigma matters


def test_flow_matching_training_learns_and_samples():
    """On a one-pattern dataset the optimal velocity field is analytic
    (v(x_σ) = x1 − x0 with x0 fixed); training must cut the loss and the
    Euler sampler must then land near the pattern."""
    cfg = DiTConfig(
        input_size=8, patch_size=2, in_channels=2, hidden_size=64,
        num_layers=2, num_heads=4, num_classes=0, remat_policy="none",
    )
    params = dit.init(cfg, jax.random.key(0))
    pattern = jax.random.normal(jax.random.key(7), (8, 8, 2))
    tx = optax.adam(2e-3)
    opt = tx.init(params)

    @jax.jit
    def step(p, o, k):
        def loss(pp):
            k1, k2 = jax.random.split(k)
            x0 = jnp.broadcast_to(pattern, (8,) + pattern.shape)
            sig = sample_sigmas(k1, 8, scheme="uniform")
            x1 = jax.random.normal(k2, x0.shape)
            v = dit.forward(pp, cfg, interpolate(x0, x1, sig), sig)
            s, n = flow_matching_loss(v, x0, x1, sig, weighting="none")
            return s / n

        l, g = jax.value_and_grad(loss)(p)
        u, o = tx.update(g, o, p)
        return optax.apply_updates(p, u), o, l

    losses = []
    for i in range(120):
        params, opt, l = step(params, opt, jax.random.key(i))
        losses.append(float(l))
    assert losses[-1] < 0.5 * losses[0], (losses[0], losses[-1])

    sample = euler_sample(
        lambda x, s: dit.forward(params, cfg, x, s),
        jax.random.key(99), (4, 8, 8, 2), steps=24,
    )
    assert np.isfinite(np.asarray(sample)).all()
    # samples should be much closer to the pattern than fresh noise is
    d_sample = float(jnp.mean(jnp.abs(sample - pattern)))
    d_noise = float(jnp.mean(jnp.abs(jax.random.normal(jax.random.key(3), sample.shape) - pattern)))
    assert d_sample < 0.7 * d_noise, (d_sample, d_noise)
