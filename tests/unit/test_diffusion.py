"""Flow-matching + DiT tests (reference: components/flow_matching/
pipeline.py interpolation/σ-sampling semantics, recipes/diffusion/train.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from automodel_tpu.diffusion import (
    euler_sample,
    flow_matching_loss,
    interpolate,
    sample_sigmas,
    time_shift,
)
from automodel_tpu.models.diffusion import dit
from automodel_tpu.models.diffusion.dit import DiTConfig

import pytest

pytestmark = pytest.mark.recipe

CFG = DiTConfig(
    input_size=8, patch_size=2, in_channels=2, hidden_size=64,
    num_layers=2, num_heads=4, num_classes=3, remat_policy="none",
)


def test_sigma_sampling_and_shift():
    s = sample_sigmas(jax.random.key(0), 4096, scheme="uniform")
    assert 0.0 <= float(s.min()) and float(s.max()) <= 1.0
    np.testing.assert_allclose(float(s.mean()), 0.5, atol=0.03)
    ln = sample_sigmas(jax.random.key(1), 4096, scheme="logit_normal")
    np.testing.assert_allclose(float(ln.mean()), 0.5, atol=0.03)

    # shift=3 pushes mass toward 1; endpoints fixed
    sig = jnp.asarray([0.0, 0.5, 1.0])
    sh = time_shift(sig, 3.0)
    np.testing.assert_allclose(np.asarray(sh), [0.0, 0.75, 1.0], rtol=1e-6)


def test_interpolation_endpoints():
    x0 = jnp.ones((2, 4, 4, 1))
    x1 = jnp.zeros((2, 4, 4, 1))
    np.testing.assert_allclose(
        np.asarray(interpolate(x0, x1, jnp.asarray([0.0, 1.0]))[0]), 1.0
    )
    np.testing.assert_allclose(
        np.asarray(interpolate(x0, x1, jnp.asarray([0.0, 1.0]))[1]), 0.0
    )


def test_dit_zero_init_outputs_zero():
    """adaLN-zero: gates and the final head are zero-init, so the untrained
    model predicts exactly zero velocity (DiT's identity start)."""
    params = dit.init(CFG, jax.random.key(0))
    x = jax.random.normal(jax.random.key(1), (2, 8, 8, 2))
    v = dit.forward(params, CFG, x, jnp.asarray([0.3, 0.9]))
    assert v.shape == x.shape
    np.testing.assert_allclose(np.asarray(v), 0.0, atol=1e-6)


def test_dit_conditioning_changes_output():
    params = dit.init(CFG, jax.random.key(0))
    # break the zero-init so conditioning has a path to the output
    params["final"]["out"]["kernel"] = 0.1 * jax.random.normal(
        jax.random.key(5), params["final"]["out"]["kernel"].shape
    )
    params["final"]["mod"]["kernel"] = 0.1 * jax.random.normal(
        jax.random.key(6), params["final"]["mod"]["kernel"].shape
    )
    x = jax.random.normal(jax.random.key(1), (2, 8, 8, 2))
    sig = jnp.asarray([0.5, 0.5])
    v0 = dit.forward(params, CFG, x, sig, class_labels=jnp.asarray([0, 0]))
    v1 = dit.forward(params, CFG, x, sig, class_labels=jnp.asarray([1, 1]))
    vs = dit.forward(params, CFG, x, jnp.asarray([0.1, 0.1]), class_labels=jnp.asarray([0, 0]))
    assert float(jnp.abs(v0 - v1).max()) > 1e-7   # class matters
    assert float(jnp.abs(v0 - vs).max()) > 1e-7   # sigma matters


def test_flow_matching_training_learns_and_samples():
    """On a one-pattern dataset the optimal velocity field is analytic
    (v(x_σ) = x1 − x0 with x0 fixed); training must cut the loss and the
    Euler sampler must then land near the pattern."""
    cfg = DiTConfig(
        input_size=8, patch_size=2, in_channels=2, hidden_size=64,
        num_layers=2, num_heads=4, num_classes=0, remat_policy="none",
    )
    params = dit.init(cfg, jax.random.key(0))
    pattern = jax.random.normal(jax.random.key(7), (8, 8, 2))
    tx = optax.adam(2e-3)
    opt = tx.init(params)

    @jax.jit
    def step(p, o, k):
        def loss(pp):
            k1, k2 = jax.random.split(k)
            x0 = jnp.broadcast_to(pattern, (8,) + pattern.shape)
            sig = sample_sigmas(k1, 8, scheme="uniform")
            x1 = jax.random.normal(k2, x0.shape)
            v = dit.forward(pp, cfg, interpolate(x0, x1, sig), sig)
            s, n = flow_matching_loss(v, x0, x1, sig, weighting="none")
            return s / n

        l, g = jax.value_and_grad(loss)(p)
        u, o = tx.update(g, o, p)
        return optax.apply_updates(p, u), o, l

    losses = []
    for i in range(120):
        params, opt, l = step(params, opt, jax.random.key(i))
        losses.append(float(l))
    assert losses[-1] < 0.5 * losses[0], (losses[0], losses[-1])

    sample = euler_sample(
        lambda x, s: dit.forward(params, cfg, x, s),
        jax.random.key(99), (4, 8, 8, 2), steps=24,
    )
    assert np.isfinite(np.asarray(sample)).all()
    # samples should be much closer to the pattern than fresh noise is
    d_sample = float(jnp.mean(jnp.abs(sample - pattern)))
    d_noise = float(jnp.mean(jnp.abs(jax.random.normal(jax.random.key(3), sample.shape) - pattern)))
    assert d_sample < 0.7 * d_noise, (d_sample, d_noise)


def test_text_conditioned_dit_simple_adapter():
    """Wan-layout text conditioning (reference: flow_matching/adapters/
    simple.py): cross-attention is live, CFG dropout zeroes embeddings,
    and the zero-init xout starts the conditioning neutral."""
    import dataclasses

    from automodel_tpu.diffusion.adapters import FlowMatchingContext, get_flow_adapter
    from automodel_tpu.models.diffusion import dit

    cfg = dit.DiTConfig(
        input_size=8, patch_size=2, in_channels=4, hidden_size=64,
        num_layers=2, num_heads=4, cross_attention_dim=32,
        remat_policy="none",
    )
    params = dit.init(cfg, jax.random.key(0))
    assert params["layers"]["xkv"]["kernel"].shape == (2, 32, 128)
    assert float(jnp.abs(params["layers"]["xout"]["kernel"]).max()) == 0.0

    rng = np.random.default_rng(0)
    lat = jnp.asarray(rng.normal(size=(2, 8, 8, 4)).astype(np.float32))
    text = jnp.asarray(rng.normal(size=(2, 6, 32)).astype(np.float32))
    sigma = jnp.asarray([0.3, 0.7], jnp.float32)

    adapter = get_flow_adapter("simple")
    ctx = FlowMatchingContext(
        noisy_latents=lat, latents=lat, sigma=sigma,
        batch={"text_embeddings": text}, rng=jax.random.key(1),
        cfg_dropout_prob=0.0,
    )
    # un-zero the adaLN-zero output head so effects can reach the output
    # (at init the DiT velocity is identically zero by design)
    params = jax.tree.map(lambda x: x, params)
    params["final"] = dict(params["final"])
    params["final"]["out"] = {
        "kernel": jnp.asarray(
            rng.normal(0, 0.1, params["final"]["out"]["kernel"].shape),
            jnp.float32,
        ),
        "bias": params["final"]["out"]["bias"],
    }
    params["final"]["mod"] = {
        "kernel": jnp.asarray(
            rng.normal(0, 0.1, params["final"]["mod"]["kernel"].shape),
            jnp.float32,
        ),
        "bias": params["final"]["mod"]["bias"],
    }
    v = adapter.forward(dit, params, cfg, adapter.prepare_inputs(cfg, ctx))
    assert v.shape == lat.shape and np.isfinite(np.asarray(v)).all()
    assert np.abs(np.asarray(v)).max() > 0

    # zero-init xout → text cannot influence the output YET
    v2 = adapter.forward(
        dit, params, cfg,
        adapter.prepare_inputs(cfg, dataclasses.replace(ctx, batch={
            "text_embeddings": text + 1.0
        })),
    )
    np.testing.assert_allclose(np.asarray(v), np.asarray(v2), atol=1e-6)

    # after perturbing xout, conditioning is live
    p2 = jax.tree.map(lambda x: x, params)
    p2["layers"] = dict(params["layers"])
    p2["layers"]["xout"] = {
        # random (a ones matrix would add a channel-uniform shift that the
        # parameter-free LayerNorms exactly cancel)
        "kernel": jnp.asarray(
            rng.normal(0, 0.1, params["layers"]["xout"]["kernel"].shape),
            jnp.float32,
        )
    }
    v3 = adapter.forward(dit, p2, cfg, adapter.prepare_inputs(cfg, ctx))
    text_b = jnp.asarray(rng.normal(size=(2, 6, 32)).astype(np.float32))
    v4 = adapter.forward(
        dit, p2, cfg,
        adapter.prepare_inputs(cfg, dataclasses.replace(ctx, batch={
            "text_embeddings": text_b
        })),
    )
    assert np.abs(np.asarray(v3) - np.asarray(v4)).max() > 1e-5

    # CFG dropout with prob 1 zeroes the text → equals zeroed embeddings
    ctx_drop = dataclasses.replace(ctx, cfg_dropout_prob=1.0)
    v5 = adapter.forward(dit, p2, cfg, adapter.prepare_inputs(cfg, ctx_drop))
    v6 = adapter.forward(
        dit, p2, cfg,
        adapter.prepare_inputs(cfg, dataclasses.replace(ctx, batch={
            "text_embeddings": jnp.zeros_like(text)
        })),
    )
    np.testing.assert_allclose(np.asarray(v5), np.asarray(v6), atol=1e-6)


@pytest.mark.recipe
def test_text_conditioned_diffusion_recipe_and_pipeline(tmp_path):
    """Wan-style text-conditioned flow matching: train via model_adapter:
    simple, export the diffusers-layout pipeline, reload, and sample with
    text embeddings."""
    import json as _json

    from automodel_tpu.cli.app import resolve_recipe_class
    from automodel_tpu.config import ConfigNode
    from automodel_tpu.diffusion.pipeline import AutoDiffusionPipeline

    cfg = ConfigNode({
        "seed": 7,
        "run_dir": str(tmp_path),
        "auto_resume": False,
        "recipe": "diffusion_train",
        "model_adapter": "simple",
        "dit": {
            "input_size": 8, "patch_size": 2, "in_channels": 4,
            "hidden_size": 64, "num_layers": 2, "num_heads": 4,
            "cross_attention_dim": 32, "remat_policy": "none",
        },
        "flow_matching": {"timestep_sampling": "logit_normal", "shift": 3.0,
                          "weighting": "linear", "cfg_drop_prob": 0.1},
        "distributed": {"dp_shard": -1},
        "dataset": {
            "_target_": "automodel_tpu.datasets.mock.MockLatentDatasetConfig",
            "num_samples": 32, "latent_size": 8, "channels": 4,
            "text_dim": 32, "text_len": 6,
        },
        "dataloader": {"microbatch_size": 8, "grad_acc_steps": 1},
        "optimizer": {"name": "adamw", "lr": 1e-3},
        "lr_scheduler": {"style": "constant", "warmup_steps": 0},
        "step_scheduler": {"max_steps": 3, "ckpt_every_steps": 100},
        "checkpoint": {"enabled": False},
    })
    r = resolve_recipe_class(cfg)(cfg)
    r.setup()
    r.run_train_validation_loop()
    recs = [
        _json.loads(l) for l in open(tmp_path / "training.jsonl") if l.strip()
    ]
    assert len(recs) == 3 and all(np.isfinite(x["loss"]) for x in recs)

    out = r.save_consolidated_hf()
    pipe = AutoDiffusionPipeline.from_pretrained(out)
    assert pipe.transformer_cfg.cross_attention_dim == 32
    rng = np.random.default_rng(1)
    text = jnp.asarray(rng.normal(size=(2, 6, 32)).astype(np.float32))
    imgs = pipe(
        jax.random.key(0), batch_size=2, text_embeddings=text,
        num_inference_steps=4, decode=False,
    )
    assert imgs.shape == (2, 8, 8, 4)
    assert np.isfinite(np.asarray(imgs)).all()
