"""Serving resilience: the degrade-don't-die acceptance contract.

- HEALTH MACHINE: healthy → degraded → draining → dead transitions are a
  pure function of the observation sequence; death counts land on the
  class-labeled failure counter exactly once.
- CHAOS PARITY: a deterministically injected replica death mid-stream is
  INVISIBLE in the tokens — every affected request recovers on a
  survivor token-for-token (greedy), the allocator identity holds on
  every surviving pool, compile-once survives recovery, and an identical
  chaos trace replays to the identical outcome.
- DEGRADED ROUTING: killing the entire prefill class collapses the
  disagg router to monolithic routing (zero wedged requests) and
  `restore()` flips it back.
- RETRY + ESCALATION: transient KV-transfer faults are absorbed by the
  deterministic-jitter retry budget; exhaustion escalates to the health
  board (re-prefill elsewhere), never into the serve loop.
- FOLLOWER LOSS: a plan-wire follower that stops reading surfaces as a
  NAMED `ReplicaFailure` within the bounded ack timeout.
- ROLLING RESTART: drain()/quiesce()/resume_admission() stop admission,
  flush residents, and reopen without dropping work.
"""

import asyncio
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from automodel_tpu.inference.generate import GenerateConfig, generate
from automodel_tpu.models.llm import decoder
from automodel_tpu.models.llm.decoder import TransformerConfig
from automodel_tpu.observability.metrics import MetricsRegistry
from automodel_tpu.resilience.faults import FaultError, FaultSpec, injected
from automodel_tpu.resilience.retry import RetryBudgetExhausted
from automodel_tpu.serving import (
    DisaggConfig,
    DisaggRouter,
    FrontendConfig,
    OnlineFrontend,
    OnlineRouter,
    PrefixCacheConfig,
    ReplicaFailure,
    ReplicaRouter,
    Request,
    ServeMeshConfig,
    ServeResilienceConfig,
    ServingConfig,
    ServingEngine,
)
from automodel_tpu.serving.plan_wire import KVStoreBroadcast
from automodel_tpu.serving.resilience import (
    DEAD,
    DEGRADED,
    DRAINING,
    HEALTHY,
    HealthBoard,
    ReplicaHealth,
    pool_identity_ok,
    transfer_with_retry,
)

CFG = TransformerConfig(
    vocab_size=64, hidden_size=32, intermediate_size=48, num_layers=2,
    num_heads=4, num_kv_heads=2, qk_norm=True, dtype=jnp.float32,
    remat_policy="none",
)
FAST = FrontendConfig(idle_sleep_s=0.0002)


@pytest.fixture(scope="module")
def params():
    return decoder.init(CFG, jax.random.key(0))


def _sc(**geo):
    base = dict(page_size=4, num_pages=24, max_slots=3, pages_per_slot=6,
                token_budget=8, prefill_chunk=4)
    base.update(geo)
    return ServingConfig(**base)


def _prompts(lens, vocab=64, seed0=0):
    return [
        [int(t) for t in np.random.default_rng(seed0 + i).integers(
            1, vocab, (l,))]
        for i, l in enumerate(lens)
    ]


def _reqs(prompts, max_new=6, arrivals=None):
    return [
        Request(prompt=list(p), max_new_tokens=max_new,
                arrival=(arrivals[i] if arrivals else 0))
        for i, p in enumerate(prompts)
    ]


def _ref(params, prompt, max_new):
    out = generate(
        params, CFG, jnp.asarray([prompt], jnp.int32), jax.random.key(0),
        GenerateConfig(max_new_tokens=max_new),
    )
    return [int(t) for t in np.asarray(out)[0, len(prompt):]]


# ---------------------------------------------------------------------------
# health state machine (pure, no engines)
# ---------------------------------------------------------------------------

def test_replica_health_transitions():
    h = ReplicaHealth("replica0", degraded_failures=2)
    assert h.state == HEALTHY and h.alive and h.admittable
    # exhaustion degrades first (still serving), then kills
    assert h.mark_exhausted(3, "transfer budget") == DEGRADED
    assert h.alive and h.admittable
    assert h.mark_exhausted(5, "transfer budget") == DEAD
    assert not h.alive and not h.admittable
    # dead is absorbing until restore
    assert h.mark_exhausted(6, "late") == DEAD
    assert h.restore() == HEALTHY and h.exhaustions == 0
    # rolling restart: draining is alive but not admittable
    assert h.mark_draining(7) == DRAINING
    assert h.alive and not h.admittable
    # a step error is one strike from any live state
    assert h.mark_dead(8, "step raised") == DEAD


def test_health_board_counts_each_death_once():
    reg = MetricsRegistry()
    board = HealthBoard(
        ["prefill0", "decode0", "decode1"],
        ServeResilienceConfig(degraded_failures=1), registry=reg,
    )
    assert board.snapshot() == {
        "prefill0": HEALTHY, "decode0": HEALTHY, "decode1": HEALTHY,
    }
    board.mark_dead("prefill0", 2, "boom")
    board.mark_dead("prefill0", 3, "boom again")  # already dead: no recount
    # degraded_failures=1 → a single exhaustion is also a death
    assert board.mark_exhausted("decode1", 4, "rotten link") == DEAD
    assert reg.counter(
        "serve_replica_failures_total", "", **{"class": "prefill"}
    ).value == 1.0
    assert reg.counter(
        "serve_replica_failures_total", "", **{"class": "decode"}
    ).value == 1.0
    assert board.n_dead() == 2 and board.alive("decode0")
    assert board.any_alive(["prefill0", "decode0"])


def test_transfer_retry_counts_attempts_and_exhausts_loudly():
    reg = MetricsRegistry()
    cfg = ServeResilienceConfig(
        transfer_retry_attempts=3,
        transfer_retry_base_delay_s=1e-4, transfer_retry_max_delay_s=1e-3,
    )
    calls = {"n": 0}

    def flaky(tag):
        calls["n"] += 1
        if calls["n"] < 3:
            raise FaultError(f"injected: {tag}")
        return tag

    assert transfer_with_retry(
        flaky, "ok", cfg=cfg, registry=reg, point="kv_transfer"
    ) == "ok"
    assert calls["n"] == 3
    retried = reg.counter(
        "serve_transfer_retries_total",
        "KV transfer / plan-wire send retry attempts",
    )
    assert retried.value == 2.0  # the two FAILED attempts

    def rotten():
        raise FaultError("injected: permanently down")

    with pytest.raises(RetryBudgetExhausted):
        transfer_with_retry(
            rotten, cfg=cfg, registry=reg, point="kv_transfer"
        )
    assert retried.value == 5.0


# ---------------------------------------------------------------------------
# offline chaos parity: replica death mid-batch
# ---------------------------------------------------------------------------

def _chaos_serve(params, prompts, arrivals, max_new):
    sc = _sc(prefix_cache=PrefixCacheConfig(enabled=True))
    router = ReplicaRouter(params, CFG, sc, ServeMeshConfig(replicas=2, tp=1))
    with injected(FaultSpec(point="serve_step_run.replica1", call=3)):
        res = router.serve_batch(_reqs(prompts, max_new, arrivals))
    return router, res


def test_replica_death_chaos_parity_offline(params):
    """Injected replica death mid-batch: every evacuated request requeues
    onto the survivor and finishes token-for-token identical to an
    undisturbed run; the surviving pool drains to the allocator identity
    and its step never recompiles. Replaying the identical chaos trace
    reproduces the identical outcome (deterministic recovery)."""
    prompts = _prompts([5, 9, 3, 7, 11, 4])
    arrivals = [0, 0, 1, 2, 3, 4]
    max_new = 6
    baseline = ServingEngine(params, CFG, _sc()).serve_batch(
        _reqs(prompts, max_new, arrivals)
    )

    router, res = _chaos_serve(params, prompts, arrivals, max_new)
    assert res["outputs"] == baseline["outputs"]
    assert all(r.finish_reason in ("eos", "length") for r in res["requests"])
    stats = res["stats"]
    assert stats["replica_health"]["replica1"] == DEAD
    assert stats["requests_recovered"] >= 1
    # compile-once on the survivor, through admission churn AND recovery
    assert stats["per_replica"][0]["compiled_signatures"] == 1
    # the class-labeled death counter fired exactly once
    assert router.obs.registry.counter(
        "serve_replica_failures_total", "", **{"class": "replica"}
    ).value == 1.0
    assert router.obs.registry.counter(
        "serve_requests_recovered_total", ""
    ).value == float(stats["requests_recovered"])

    # identical trace → identical recovery (fresh router, same fault)
    router2, res2 = _chaos_serve(params, prompts, arrivals, max_new)
    assert res2["outputs"] == res["outputs"]
    assert res2["stats"]["requests_recovered"] == stats["requests_recovered"]


def test_resilience_disabled_restores_fail_fast(params):
    router = ReplicaRouter(
        params, CFG, _sc(), ServeMeshConfig(replicas=2, tp=1),
        resilience=ServeResilienceConfig(enabled=False),
    )
    with injected(FaultSpec(point="serve_step_run.replica0", call=1)):
        with pytest.raises(FaultError):
            router.serve_batch(_reqs(_prompts([5, 7]), 4))


def test_last_replica_death_raises_named_failure(params):
    router = ReplicaRouter(params, CFG, _sc(), ServeMeshConfig(replicas=2,
                                                               tp=1))
    with injected(
        FaultSpec(point="serve_step_run.replica0", call=2),
        FaultSpec(point="serve_step_run.replica1", call=2),
    ):
        with pytest.raises(ReplicaFailure) as ei:
            router.serve_batch(_reqs(_prompts([5, 7, 6]), 6))
    assert ei.value.replica in ("replica0", "replica1")


# ---------------------------------------------------------------------------
# online chaos parity: live streams adopted across a death
# ---------------------------------------------------------------------------

def test_online_streams_survive_replica_death(params):
    """A replica death under LIVE streams: the dying frontend's residents
    are adopted by the survivor — the client keeps its TokenStream, the
    tokens are exactly the greedy continuation (never lost, never
    duplicated), and the stream ends with its NORMAL finish reason,
    `recovered` marking the detour."""
    sc = _sc(prefix_cache=PrefixCacheConfig(enabled=True))
    router = ReplicaRouter(params, CFG, sc, ServeMeshConfig(replicas=2,
                                                            tp=1))
    prompts = _prompts([5, 9, 3, 7])
    max_new = 8

    async def run():
        orouter = OnlineRouter(router, FAST).start()
        streams = []
        for p in prompts:
            s = orouter.submit(Request(prompt=list(p),
                                       max_new_tokens=max_new))
            streams.append(s)
            # let the chosen frontend pull the arrival into its scheduler
            # so the next route probes real occupancy (deterministic
            # spread over both replicas)
            fe = orouter.frontends[orouter._by_rid[s.rid]]
            while fe._arrivals.qsize():
                await asyncio.sleep(0)
        outs = await asyncio.gather(*(s.collect() for s in streams))
        stats = await orouter.close()
        return orouter, outs, stats, streams

    with injected(FaultSpec(point="serve_step_run.replica1", call=3)):
        orouter, outs, stats, streams = asyncio.run(run())

    for p, out in zip(prompts, outs):
        assert out == _ref(params, p, max_new)
    assert all(s.finish_reason == "length" for s in streams)
    assert stats["replica_health"]["replica1"] == DEAD
    assert stats["recovered"] >= 1
    assert sum(s.recovered for s in streams) >= 1
    assert stats["per_replica"][0]["compiled_signatures"] == 1
    # the survivor drained: every page free or prefix-cached
    assert pool_identity_ok(orouter.frontends[0].sched)


# ---------------------------------------------------------------------------
# disagg: degraded-mode routing + transfer retry escalation
# ---------------------------------------------------------------------------

def test_prefill_class_death_degrades_to_monolithic(params):
    """Killing the ENTIRE prefill class must not wedge the queue: the
    router collapses to monolithic routing (decode replicas take prefill
    chunks, requests complete in place), outputs stay token-identical,
    and restore() returns the router to disagg."""
    sc = _sc()
    prompts = _prompts([5, 9, 3, 7])
    max_new = 6
    baseline = ServingEngine(params, CFG, sc).serve_batch(
        _reqs(prompts, max_new)
    )
    router = DisaggRouter(
        params, CFG, sc,
        DisaggConfig(enabled=True, transfer_pages=4,
                     prefill_token_budget=16),
    )
    with injected(FaultSpec(point="serve_step_run.prefill0", call=1)):
        res = router.serve_batch(_reqs(prompts, max_new))
    assert res["outputs"] == baseline["outputs"]
    assert all(r.finish_reason in ("eos", "length") for r in res["requests"])
    stats = res["stats"]
    assert stats["degraded"] is True
    assert stats["replica_health"]["prefill0"] == DEAD
    assert stats["requests_recovered"] >= 1
    assert router.obs.registry.gauge(
        "serve_degraded_mode", ""
    ).value == 1.0
    # the slice came back: disagg routing resumes
    router.restore("prefill0")
    assert router.degraded is False
    res2 = router.serve_batch(_reqs(prompts, max_new))
    assert res2["outputs"] == baseline["outputs"]
    assert res2["stats"]["handoffs"] >= 1


def test_transfer_faults_absorbed_by_retry(params):
    """Two transient KV-transfer faults: the deterministic-jitter retry
    budget absorbs them (attempts counted), nothing escalates, parity
    holds."""
    sc = _sc()
    prompts = _prompts([5, 9, 3])
    max_new = 6
    baseline = ServingEngine(params, CFG, sc).serve_batch(
        _reqs(prompts, max_new)
    )
    router = DisaggRouter(
        params, CFG, sc,
        DisaggConfig(enabled=True, transfer_pages=4,
                     prefill_token_budget=16),
    )
    with injected(FaultSpec(point="kv_transfer", times=2)):
        res = router.serve_batch(_reqs(prompts, max_new))
    assert res["outputs"] == baseline["outputs"]
    assert res["stats"]["requests_recovered"] == 0
    assert res["stats"]["replica_health"] == {
        "prefill0": HEALTHY, "decode0": HEALTHY,
    }
    assert router.obs.registry.counter(
        "serve_transfer_retries_total", ""
    ).value >= 2.0


def test_transfer_exhaustion_escalates_to_reprefill(params):
    """Retry budget exhausted on a handoff: the decode replica degrades
    (not dead — its step is fine), the admission rolls back with pins
    dropped, and the request re-prefills from scratch — still finishing
    token-identical."""
    sc = _sc()
    prompts = _prompts([5, 9, 3])
    max_new = 6
    baseline = ServingEngine(params, CFG, sc).serve_batch(
        _reqs(prompts, max_new)
    )
    router = DisaggRouter(
        params, CFG, sc,
        DisaggConfig(enabled=True, transfer_pages=4,
                     prefill_token_budget=16),
        resilience=ServeResilienceConfig(
            transfer_retry_attempts=2,
            transfer_retry_base_delay_s=1e-4,
            transfer_retry_max_delay_s=1e-3,
        ),
    )
    # 3 faults / 2 attempts per budget: the first handoff exhausts its
    # budget (2 failures → escalate), the re-prefilled handoff eats the
    # third fault and succeeds on retry
    with injected(FaultSpec(point="kv_transfer", times=3)):
        res = router.serve_batch(_reqs(prompts, max_new))
    assert res["outputs"] == baseline["outputs"]
    stats = res["stats"]
    assert stats["requests_recovered"] >= 1
    assert stats["replica_health"]["decode0"] == DEGRADED
    assert stats["degraded"] is False  # prefill class is intact
    assert router.obs.registry.counter(
        "serve_requests_recovered_total", ""
    ).value >= 1.0


# ---------------------------------------------------------------------------
# rolling restart: drain / quiesce / resume
# ---------------------------------------------------------------------------

def test_drain_quiesce_resume_admission(params):
    """drain() stops ADMISSION while residents finish; quiesce() returns
    only once nothing is resident; resume_admission() reopens — no work
    dropped anywhere."""
    engine = ServingEngine(params, CFG, _sc())
    prompts = _prompts([5, 9, 4])

    async def run():
        fe = OnlineFrontend(engine, FAST).start()
        live = [fe.submit(Request(prompt=list(p), max_new_tokens=6))
                for p in prompts[:2]]
        consumers = [asyncio.ensure_future(s.collect()) for s in live]
        await fe.wait_step(2)
        fe.drain()
        shed = fe.submit(Request(prompt=list(prompts[2]), max_new_tokens=6))
        shed_out = await shed.collect()
        await fe.quiesce()
        assert not fe.sched.has_work
        fe.resume_admission()
        late = fe.submit(Request(prompt=list(prompts[2]), max_new_tokens=6))
        late_out = await late.collect()
        outs = [await c for c in consumers]
        stats = await fe.close()
        return fe, outs, shed, shed_out, late, late_out, stats

    fe, outs, shed, shed_out, late, late_out, stats = asyncio.run(run())
    for p, out in zip(prompts[:2], outs):
        assert out == _ref(params, p, 6)
    assert shed.finish_reason == "shed" and shed_out == []
    assert late.finish_reason == "length"
    assert late_out == _ref(params, prompts[2], 6)
    assert stats["finished"] == 2 + 1 + 1  # 2 drained + 1 shed + 1 late
    assert stats["finish_reasons"]["shed"] == 1
    assert stats["draining"] is False
    assert pool_identity_ok(fe.sched)


# ---------------------------------------------------------------------------
# mid-recovery shed arithmetic (the deadline-accounting bugfix)
# ---------------------------------------------------------------------------

def test_recovery_backlog_prices_reprefill_into_shedding(params):
    """An adopted-but-not-yet-queued request re-prefills its whole
    `known`; admission arithmetic must count that backlog. The old
    formula (device + waiting only) admitted deadline-doomed work
    mid-recovery — this pins the corrected term."""
    engine = ServingEngine(params, CFG, _sc())
    fe = OnlineFrontend(engine, FAST)  # never started: pure arithmetic
    big = Request(prompt=list(range(1, 41)), max_new_tokens=4)  # 40 to re-feed
    fe._adopted.append((big, None, 0))
    assert fe._recovery_backlog() == 40

    probe = Request(prompt=list(range(1, 9)), max_new_tokens=4)  # 8 pending
    probe.deadline = fe.step_idx + 4
    base = fe._backlog() + fe._waiting_backlog()
    # without the recovery term the request looks easily reachable...
    assert fe._reachable(probe, base) is True
    # ...but the 40-token re-prefill ahead of it makes the deadline
    # unreachable — the fixed formula sheds it at the door
    assert fe._reachable(probe, base + fe._recovery_backlog()) is False


# ---------------------------------------------------------------------------
# plan-wire follower loss: bounded-timeout acks
# ---------------------------------------------------------------------------

class _FakeCoordClient:
    """Hermetic stand-in for the jax.distributed coordination KV store:
    blocking gets honor the timeout against a condition variable."""

    def __init__(self):
        self._kv: dict = {}
        self._cond = threading.Condition()

    def key_value_set_bytes(self, k, b):
        with self._cond:
            self._kv[k] = bytes(b)
            self._cond.notify_all()

    def key_value_delete(self, k):
        with self._cond:
            self._kv.pop(k, None)

    def blocking_key_value_get_bytes(self, k, timeout_ms):
        deadline = time.monotonic() + timeout_ms / 1e3
        with self._cond:
            while k not in self._kv:
                left = deadline - time.monotonic()
                if left <= 0:
                    raise TimeoutError(f"no key {k} within {timeout_ms}ms")
                self._cond.wait(left)
            return self._kv[k]

    def keys(self):
        with self._cond:
            return set(self._kv)


def test_plan_wire_acks_roundtrip_with_live_follower():
    kv = _FakeCoordClient()
    lead = KVStoreBroadcast(6, True, client=kv, ack_every=2,
                            ack_timeout_ms=2_000, num_followers=1)
    follower = KVStoreBroadcast(6, False, client=kv, ack_every=2,
                                follower_id=1)
    bufs = [np.full(6, i, np.int32) for i in range(4)]
    got = []

    def consume():
        for _ in bufs:
            got.append(follower.recv())

    t = threading.Thread(target=consume)
    t.start()
    for b in bufs:  # acks due after seq 1 and seq 3; both arrive in time
        lead.send(b)
    t.join(timeout=10)
    assert not t.is_alive()
    assert [list(g) for g in got] == [list(b) for b in bufs]
    # the follower acked on receipt at every ack-due frame
    assert "planwire/ack/1/1" in kv.keys()
    assert "planwire/ack/1/3" in kv.keys()


def test_plan_wire_dead_follower_surfaces_as_named_failure():
    kv = _FakeCoordClient()
    lead = KVStoreBroadcast(6, True, client=kv, ack_every=2,
                            ack_timeout_ms=30, num_followers=1)
    lead.send(np.zeros(6, np.int32))  # seq 0: no ack due yet
    with pytest.raises(ReplicaFailure) as ei:
        lead.send(np.ones(6, np.int32))  # seq 1: ack due, nobody home
    assert ei.value.replica == "follower1"
    assert "seq 1" in ei.value.reason


def test_plan_wire_acks_disabled_never_blocks():
    kv = _FakeCoordClient()
    lead = KVStoreBroadcast(4, True, client=kv, ack_every=0,
                            num_followers=1)
    for i in range(6):
        lead.send(np.full(4, i, np.int32))
    assert not any(k.startswith("planwire/ack") for k in kv.keys())
