"""Property tests for the static pipeline schedule tables.

The tables ARE the schedule — `pipeline_train_{1f1b,zb,interleaved}` just
replay them inside a lax.scan — so completeness (every (stage, microbatch)
op exactly once) and dependency order (≥1-tick latency so the ppermute
streams deliver in time) here guarantee no silent gradient loss in any
executor, for shapes far beyond what the shard_map parity tests can afford
to compile."""

import numpy as np
import pytest

from automodel_tpu.parallel.pp import (
    interleaved_1f1b_tables,
    one_f_one_b_tables,
    zero_bubble_tables,
)

SHAPES = [(2, 2), (4, 2), (4, 4), (8, 4), (6, 3), (16, 4), (8, 8)]


def _completion_ticks(tab, M, P):
    """tab (T, P) of microbatch-or-minus-1 → done[p][m] = tick, asserting
    each (stage, microbatch) appears exactly once."""
    T = tab.shape[0]
    done = np.full((P, M), -1, np.int64)
    for t in range(T):
        for p in range(P):
            m = tab[t, p]
            if m < 0:
                continue
            assert m < M, (t, p, m)
            assert done[p, m] == -1, f"duplicate op (stage={p}, mb={m})"
            done[p, m] = t
    assert (done >= 0).all(), f"missing ops at {np.argwhere(done < 0)}"
    return done


@pytest.mark.parametrize("M,P", SHAPES)
def test_1f1b_tables_complete_and_ordered(M, P):
    fwd, bwd = one_f_one_b_tables(M, P)
    f_done = _completion_ticks(fwd, M, P)
    b_done = _completion_ticks(bwd, M, P)
    for m in range(M):
        for p in range(P):
            if p > 0:  # fwd flows down the ring with ≥1-tick latency
                assert f_done[p, m] > f_done[p - 1, m], (m, p)
            if p < P - 1:  # bwd flows back up
                assert b_done[p, m] > b_done[p + 1, m], (m, p)
            # a stage backprops a microbatch only after forwarding it
            assert b_done[p, m] > f_done[p, m], (m, p)


@pytest.mark.parametrize("M,P", SHAPES)
def test_1f1b_tables_respect_memory_bound(M, P):
    """At most P-p microbatches in flight (fwd done, bwd pending) at stage
    p — the 1F1B memory bound that keeps the mod-P stash collision-free."""
    fwd, bwd = one_f_one_b_tables(M, P)
    T = fwd.shape[0]
    for p in range(P):
        in_flight = 0
        for t in range(T):
            in_flight += int(fwd[t, p] >= 0)
            assert in_flight <= P - p, (p, t)
            in_flight -= int(bwd[t, p] >= 0)


@pytest.mark.parametrize("M,P", SHAPES)
def test_zb_tables_complete_and_ordered(M, P):
    fwd, bwd, wgt = zero_bubble_tables(M, P)
    f_done = _completion_ticks(fwd, M, P)
    b_done = _completion_ticks(bwd, M, P)
    w_done = _completion_ticks(wgt, M, P)
    for m in range(M):
        for p in range(P):
            if p > 0:
                assert f_done[p, m] > f_done[p - 1, m], (m, p)
            if p < P - 1:
                assert b_done[p, m] > b_done[p + 1, m], (m, p)
            assert b_done[p, m] > f_done[p, m], (m, p)
            # W consumes the cotangent B stashed — strictly after B
            assert w_done[p, m] > b_done[p, m], (m, p)


@pytest.mark.parametrize("M,P", SHAPES)
def test_zb_tables_stash_bounds(M, P):
    """The (f-w) < P and (b-w) < P constraints are what make the mod-P
    input/cotangent stashes collision-free; verify them on the emitted
    tables, not just in the builder."""
    fwd, bwd, wgt = zero_bubble_tables(M, P)
    T = fwd.shape[0]
    for p in range(P):
        nf = nb = nw = 0
        for t in range(T):
            nf += int(fwd[t, p] >= 0)
            nb += int(bwd[t, p] >= 0)
            assert nf - nw <= P, (p, t)
            assert nb - nw <= P, (p, t)
            nw += int(wgt[t, p] >= 0)


@pytest.mark.parametrize("M,P", SHAPES)
def test_zb_span_close_to_1f1b(M, P):
    """ZB-H1's whole point: W-fills keep the span from growing much beyond
    1F1B's while eliminating drain bubbles."""
    t_zb = zero_bubble_tables(M, P)[0].shape[0]
    t_1f1b = one_f_one_b_tables(M, P)[0].shape[0]
    assert t_zb <= t_1f1b + M, (M, P, t_zb, t_1f1b)


@pytest.mark.parametrize(
    "M,P,V", [(2, 2, 2), (4, 2, 2), (4, 2, 3), (8, 4, 2), (4, 4, 2)]
)
def test_interleaved_tables_complete_and_ordered(M, P, V):
    """Entries encode v*M + m for virtual stage s = v*P + p living on
    device p; decode back to (global stage, microbatch) and check the
    virtual-stage chain order."""
    S = P * V
    fwd, bwd = interleaved_1f1b_tables(M, P, V)
    T = fwd.shape[0]
    f_done = np.full((S, M), -1, np.int64)
    b_done = np.full((S, M), -1, np.int64)
    for tab, done in ((fwd, f_done), (bwd, b_done)):
        for t in range(T):
            for p in range(P):
                a = tab[t, p]
                if a < 0:
                    continue
                v, m = divmod(int(a), M)
                s = v * P + p  # cyclic device mapping: stage s on device s%P
                assert v < V and m < M, (t, p, a)
                assert done[s, m] == -1, f"duplicate (stage={s}, mb={m})"
                done[s, m] = t
    assert (f_done >= 0).all() and (b_done >= 0).all()
    for m in range(M):
        for s in range(S):
            if s > 0:
                assert f_done[s, m] > f_done[s - 1, m], (m, s)
            if s < S - 1:
                assert b_done[s, m] > b_done[s + 1, m], (m, s)
            assert b_done[s, m] > f_done[s, m], (m, s)


@pytest.mark.parametrize("M,P,V", [(4, 2, 2), (8, 4, 2)])
def test_interleaved_one_op_per_device_tick(M, P, V):
    """The executor runs at most one fwd and one bwd slot per device per
    tick; the encoding must never ask for two (trivially true by table
    shape — this documents the contract and guards a refactor to packed
    encodings)."""
    fwd, bwd = interleaved_1f1b_tables(M, P, V)
    assert fwd.shape == bwd.shape
    assert fwd.shape[1] == P
