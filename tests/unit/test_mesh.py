import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec

from automodel_tpu.distributed import MeshConfig
from automodel_tpu.parallel import AxisRules, logical_to_shardings, with_logical_constraint


def test_mesh_build_infer_dp():
    ctx = MeshConfig(tp=2).build()
    assert ctx.sizes["tp"] == 2
    assert ctx.sizes["dp_shard"] == 4  # inferred from 8 virtual devices
    assert ctx.num_devices == 8
    assert ctx.dp_size == 4
    assert ctx.batch_size_divisor == 4


def test_mesh_build_explicit_mismatch():
    with pytest.raises(ValueError):
        MeshConfig(tp=2, dp_shard=8).build()
    with pytest.raises(ValueError):
        MeshConfig(tp=3).build()  # 8 % 3 != 0


def test_spec_aliases():
    ctx = MeshConfig(tp=2, cp=2, dp_shard=2).build()
    spec = ctx.spec("batch", "cp", None)
    assert spec == PartitionSpec(("dp_replicate", "dp_shard", "ep"), "cp", None)
    assert ctx.axis_size("dp") == 2
    assert ctx.axis_size("dp_cp") == 4


def test_axis_rules_spec_dedup():
    ctx = MeshConfig(tp=2).build()
    rules = AxisRules()
    # embed→dp_shard, mlp→tp
    spec = rules.spec(("embed", "mlp"), ctx)
    assert spec == PartitionSpec("dp_shard", "tp")
    # two logical axes mapping to tp: second loses it
    spec2 = rules.spec(("heads", "mlp"), ctx)
    assert spec2 == PartitionSpec("tp", None)


def test_logical_to_shardings_divisibility_fallback():
    ctx = MeshConfig(tp=2, dp_shard=4).build()
    specs = {"w": ("embed", "mlp")}
    shapes = {"w": (6, 128)}  # 6 not divisible by dp_shard=4
    sh = logical_to_shardings(specs, ctx, shapes=shapes)
    assert sh["w"].spec == PartitionSpec(None, "tp")


def test_param_sharding_places_data():
    ctx = MeshConfig(tp=2, dp_shard=4).build()
    sh = logical_to_shardings({"w": ("embed", "mlp")}, ctx)
    w = jax.device_put(np.zeros((8, 16), np.float32), sh["w"])
    assert w.sharding.spec == PartitionSpec("dp_shard", "tp")
    # each device holds 1/8 of the array
    assert w.addressable_shards[0].data.shape == (2, 8)


def test_with_logical_constraint_in_jit():
    ctx = MeshConfig(dp_shard=4, tp=2).build()

    @jax.jit
    def f(x):
        return with_logical_constraint(x * 2, ("act_batch", "act_seq", None), ctx)

    x = np.zeros((8, 16, 4), np.float32)
    y = f(x)
    assert y.shape == x.shape
