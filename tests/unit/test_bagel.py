"""BAGEL unified multimodal: MoT routing, mixed-modal mask, flow matching,
adapter round-trip, training recipe.

Reference: nemo_automodel/components/models/bagel/ (model.py,
modeling_qwen2_packed.py, attention_masks.py, state_dict_adapter.py).
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from automodel_tpu.models.omni import bagel
from automodel_tpu.models.registry import get_model_spec

BAGEL_HF = {
    "architectures": ["BagelForUnifiedMultimodal"],
    "model_type": "bagel",
    "visual_gen": True,
    "llm_config": {
        "vocab_size": 128, "hidden_size": 32, "intermediate_size": 64,
        "num_hidden_layers": 2, "num_attention_heads": 4,
        "num_key_value_heads": 2, "qk_norm": True,
    },
    "vision_config": {
        "hidden_size": 32, "intermediate_size": 48, "num_hidden_layers": 2,
        "num_attention_heads": 2, "image_size": 56, "patch_size": 14,
    },
    "vit_max_num_patch_per_side": 8,
    "latent_patch_size": 2,
    "max_latent_size": 8,
    "vae_config": {"z_channels": 4, "downsample": 8},
}


def _setup(visual_gen=True):
    hf = dict(BAGEL_HF, visual_gen=visual_gen)
    spec = get_model_spec(hf)
    cfg = spec.config_from_hf(hf, dtype=jnp.float32, remat_policy="none")
    return spec, cfg, bagel.init(cfg, jax.random.key(0))


def _batch(cfg, B=2, S=40):
    rng = np.random.default_rng(0)
    n_vit = (cfg.vision.image_size // cfg.vision.patch_size) ** 2  # 16
    n_vae = 16 if cfg.visual_gen else 0
    ids = rng.integers(1, 128, (B, S), dtype=np.int32)
    tt = np.zeros((B, S), np.int32)
    tt[:, 2 : 2 + n_vit] = 1
    if n_vae:
        tt[:, 20 : 20 + n_vae] = 2
    pix = rng.normal(size=(B, 56, 56, 3)).astype(np.float32)
    lat = rng.normal(size=(B, 4, 8, 8)).astype(np.float32)
    t = rng.normal(size=(B,)).astype(np.float32)
    return (
        jnp.asarray(ids), jnp.asarray(tt), jnp.asarray(pix),
        jnp.asarray(lat), jnp.asarray(t),
    )


@pytest.mark.slow
def test_config_and_init_shapes():
    spec, cfg, params = _setup()
    assert cfg.visual_gen and cfg.qk_norm
    lm = params["language_model"]
    assert set(lm["layers"]) == {"und", "gen"}
    assert lm["layers"]["gen"]["q_proj"]["kernel"].shape == (2, 32, 32)
    assert "gen" in lm["final_norm"]
    # llm2vae zero-init: stage 2 starts with zero MSE signal
    assert float(jnp.abs(params["llm2vae"]["kernel"]).max()) == 0.0
    # frozen sin/cos tables are computed constants, NOT parameters — they
    # can neither receive gradients nor weight-decay drift
    assert "vit_pos_embed" not in params
    assert "latent_pos_embed" not in params


def test_attention_mask_semantics():
    """Pinned to attention_masks.py predicates: causal text; bidirectional
    within a vit region; NOISE (vae) keys invisible outside their region —
    later text cannot attend the noisy latents."""
    tt = jnp.asarray([[0, 1, 1, 0, 2, 2, 0]])
    seg = jnp.zeros((1, 7), jnp.int32)
    m = np.asarray(bagel.bagel_attention_mask(tt, seg))[0]
    assert m[1, 2] and m[2, 1]          # vit region bidirectional
    assert m[4, 5] and m[5, 4]          # vae region bidirectional
    assert not m[0, 1]                  # text cannot look ahead
    assert m[3, 1] and m[3, 2]          # later text sees vit (causal)
    assert not m[6, 4] and not m[6, 5]  # later text NEVER sees noise keys
    assert m[4, 0] and m[4, 3]          # vae sees earlier text (causal)
    assert m[6, 0] and m[6, 3]

    # cross-sample isolation
    seg2 = jnp.asarray([[0, 0, 0, 0, 1, 1, 1]])
    m2 = np.asarray(bagel.bagel_attention_mask(tt, seg2))[0]
    assert not m2[4, 0] and not m2[6, 3]


@pytest.mark.slow
def test_forward_joint_losses():
    spec, cfg, params = _setup()
    ids, tt, pix, lat, t = _batch(cfg)
    logits, gen_out = bagel.forward(
        params, cfg, ids, tt, pixel_values=pix, latents=lat, timesteps=t,
        rng=jax.random.key(1),
    )
    assert logits.shape == (2, 40, 128)
    assert np.isfinite(np.asarray(logits)).all()
    assert gen_out is not None
    assert gen_out["velocity_pred"].shape == (2, 16, 16)  # (B, Nlat, p²C)
    labels = jnp.where(tt == 0, ids, -100)
    ce, n, mse = bagel.bagel_losses(logits, gen_out, labels, tt, t)
    assert float(n) > 0 and np.isfinite(float(ce))
    # llm2vae is zero-init → velocity_pred is bias-only zeros → mse equals
    # mean of target²; after one grad step it must move (tested via recipe)
    tgt = np.asarray(gen_out["target"])
    w = np.asarray(gen_out["t"]) > 0
    expect = (tgt[w] ** 2).mean()
    np.testing.assert_allclose(float(mse), expect, rtol=1e-4)


@pytest.mark.slow
def test_gen_expert_routing_is_live():
    """Zeroing the GEN experts changes vae-token hidden states but leaves
    pure-text rows untouched (the MoT contract)."""
    spec, cfg, params = _setup()
    ids, tt, pix, lat, t = _batch(cfg)
    h1, _ = bagel.forward(
        params, cfg, ids, tt, pixel_values=pix, latents=lat, timesteps=t,
        rng=jax.random.key(1), return_hidden=True,
    )
    z = jax.tree.map(lambda x: x, params)
    z["language_model"] = dict(params["language_model"])
    z["language_model"]["layers"] = dict(params["language_model"]["layers"])
    z["language_model"]["layers"]["gen"] = jax.tree.map(
        jnp.zeros_like, params["language_model"]["layers"]["gen"]
    )
    h2, _ = bagel.forward(
        z, cfg, ids, tt, pixel_values=pix, latents=lat, timesteps=t,
        rng=jax.random.key(1), return_hidden=True,
    )
    d = np.abs(np.asarray(h1) - np.asarray(h2)).max(axis=-1)  # (B, S)
    ttn = np.asarray(tt)
    assert d[ttn == 2].max() > 1e-6          # gen tokens changed
    # und tokens BEFORE any vae position are untouched (vae keys are
    # invisible to und queries only when und precedes... noise keys are
    # never visible to outside queries, so ALL und tokens are untouched)
    assert d[ttn != 2].max() < 1e-5


def test_understanding_only_stage1():
    spec, cfg, params = _setup(visual_gen=False)
    assert "gen" not in params["language_model"]["layers"]
    ids, tt, pix, _, _ = _batch(cfg)
    logits, gen_out = bagel.forward(params, cfg, ids, tt, pixel_values=pix)
    assert gen_out is None
    assert np.isfinite(np.asarray(logits)).all()


@pytest.mark.slow
def test_bagel_adapter_roundtrip():
    from automodel_tpu.checkpoint.hf_adapter import get_adapter

    spec, cfg, params = _setup()
    ad = get_adapter(spec.adapter_name, cfg, **spec.adapter_kwargs)
    sd = dict(ad.to_hf(params))
    assert "language_model.model.layers.0.self_attn.q_proj_moe_gen.weight" in sd
    assert "language_model.model.layers.1.mlp_moe_gen.down_proj.weight" in sd
    assert "language_model.model.norm_moe_gen.weight" in sd
    assert "vit_model.vision_model.encoder.layers.0.self_attn.q_proj.weight" in sd
    assert "time_embedder.mlp.0.weight" in sd
    assert sd["llm2vae.weight"].shape == (16, 32)
    assert "vit_pos_embed.pos_embed" in sd
    p2 = ad.from_hf(lambda k: np.asarray(sd[k]))
    ids, tt, pix, lat, t = _batch(cfg)
    o1, _ = bagel.forward(
        params, cfg, ids, tt, pixel_values=pix, latents=lat, timesteps=t,
        rng=jax.random.key(2),
    )
    o2, _ = bagel.forward(
        jax.tree.map(jnp.asarray, p2), cfg, ids, tt, pixel_values=pix,
        latents=lat, timesteps=t, rng=jax.random.key(2),
    )
    np.testing.assert_allclose(np.asarray(o1), np.asarray(o2), atol=1e-5)


@pytest.mark.recipe
@pytest.mark.slow  # compile-heavy recipe; bagel fwd/adapter tests stay tier-1
def test_bagel_recipe_trains(tmp_path):
    from automodel_tpu.cli.app import resolve_recipe_class
    from automodel_tpu.config import ConfigNode

    cfg = ConfigNode({
        "seed": 7,
        "run_dir": str(tmp_path),
        "auto_resume": False,
        "recipe": "bagel_finetune",
        "model": {"hf_config": BAGEL_HF, "dtype": "float32", "remat_policy": "none"},
        "distributed": {"dp_shard": -1},
        "dataset": {
            "_target_": "automodel_tpu.datasets.bagel_mock.MockBagelDatasetConfig",
            "num_samples": 32, "seq_len": 48, "vocab_size": 128,
            "image_size": 56, "patch_size": 14,
            "latent_size": 8, "latent_patch": 2, "z_channels": 4,
        },
        "dataloader": {"microbatch_size": 8, "grad_acc_steps": 1},
        "optimizer": {"name": "adamw", "lr": 1e-3},
        "lr_scheduler": {"style": "constant", "warmup_steps": 0},
        "step_scheduler": {"max_steps": 3, "ckpt_every_steps": 100},
        "checkpoint": {"enabled": False},
    })
    r = resolve_recipe_class(cfg)(cfg)
    r.setup()
    r.run_train_validation_loop()
    recs = [json.loads(l) for l in open(tmp_path / "training.jsonl") if l.strip()]
    assert len(recs) == 3
    assert all(np.isfinite(x["loss"]) for x in recs)
    assert all("mse" in x for x in recs)
    # the zero-init MSE head starts learning: mse moves from its t=0 value
    assert recs[0]["mse"] != recs[-1]["mse"]
