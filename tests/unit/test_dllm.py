"""Masked-diffusion LM (MDLM) tests.

Semantics anchors: corrupt_uniform must mask only supervised positions with
per-sequence probability p=(1-eps)t+eps (reference: datasets/dllm/
corruption.py:73); the loss is CE at masked∩supervised weighted 1/p over
the supervised count (reference: loss/dllm_loss.py:105)."""

import jax
import jax.numpy as jnp
import numpy as np

from automodel_tpu.dllm import corrupt_blockwise, corrupt_uniform
from automodel_tpu.dllm.mdlm import mdlm_loss_from_hidden
from automodel_tpu.dllm.sampler import generate_mdlm

import pytest

pytestmark = pytest.mark.recipe

MASK = 99


def test_corrupt_uniform_respects_loss_mask():
    rng = jax.random.key(0)
    ids = jnp.ones((4, 32), jnp.int32) * 5
    lm = jnp.zeros((4, 32), bool).at[:, 16:].set(True)
    noisy, nm, p = corrupt_uniform(rng, ids, lm, MASK, eps=1e-3)
    # unsupervised half untouched
    np.testing.assert_array_equal(np.asarray(noisy[:, :16]), 5)
    assert not np.asarray(nm[:, :16]).any()
    # masked positions really carry [MASK]
    assert np.asarray(jnp.where(nm, noisy == MASK, True)).all()
    # p constant per sequence, in [eps, 1]
    pv = np.asarray(p)
    assert (pv >= 1e-3 - 1e-9).all() and (pv <= 1.0).all()
    assert np.allclose(pv, pv[:, :1])


def test_corrupt_uniform_rate_matches_p():
    rng = jax.random.key(1)
    ids = jnp.ones((8, 4096), jnp.int32)
    lm = jnp.ones((8, 4096), bool)
    _, nm, p = corrupt_uniform(rng, ids, lm, MASK)
    rate = np.asarray(nm).mean(axis=1)
    np.testing.assert_allclose(rate, np.asarray(p)[:, 0], atol=0.03)


def test_corrupt_blockwise_block_structure():
    rng = jax.random.key(2)
    ids = jnp.ones((2, 64), jnp.int32)
    lm = jnp.ones((2, 64), bool)
    _, _, p = corrupt_blockwise(rng, ids, lm, MASK, block_size=16)
    pv = np.asarray(p).reshape(2, 4, 16)
    # constant within a block, differing across blocks
    assert np.allclose(pv, pv[:, :, :1])
    assert len(np.unique(pv[0, :, 0])) > 1


def test_mdlm_loss_weighting_oracle():
    """1/p weighting: equal CE everywhere → loss = CE · E[1/p · 1{masked}]
    computed exactly from the realized masks."""
    rng = np.random.default_rng(0)
    B, L, H, V = 2, 16, 8, 32
    hidden = jnp.asarray(rng.normal(0, 1, (B, L, H)), jnp.float32)
    kernel = jnp.asarray(rng.normal(0, 0.2, (H, V)), jnp.float32)
    clean = jnp.asarray(rng.integers(0, V, (B, L)), jnp.int32)
    nm = jnp.asarray(rng.random((B, L)) < 0.5)
    p = jnp.full((B, L), 0.25, jnp.float32)
    lm = jnp.ones((B, L), bool)

    s, n = mdlm_loss_from_hidden(hidden, kernel, clean, nm, p, lm, chunk_size=8)
    # oracle: dense per-token CE
    logits = np.asarray(hidden) @ np.asarray(kernel)
    lse = np.log(np.exp(logits).sum(-1))
    picked = np.take_along_axis(logits, np.asarray(clean)[..., None], -1)[..., 0]
    ce = lse - picked
    expect = (ce * np.asarray(nm) / 0.25).sum()
    np.testing.assert_allclose(float(s), expect, rtol=1e-5)
    assert float(n) == B * L


def test_mdlm_training_reduces_loss():
    """A tiny bidirectional decoder must learn to reconstruct a fixed
    sequence under masking."""
    import optax

    from automodel_tpu.models.llm.decoder import TransformerConfig
    from automodel_tpu.models.llm import decoder

    cfg = TransformerConfig(
        vocab_size=64, hidden_size=32, intermediate_size=64, num_layers=2,
        num_heads=4, num_kv_heads=2, dtype=jnp.float32, remat_policy="none",
        causal=False, tie_word_embeddings=False,
    )
    params = decoder.init(cfg, jax.random.key(0))
    ids = jnp.asarray(np.random.default_rng(1).integers(1, 60, (4, 24)), jnp.int32)
    lm = jnp.ones(ids.shape, bool)
    tx = optax.adam(3e-3)
    opt = tx.init(params)

    @jax.jit
    def step(p, o, k):
        def loss(pp):
            noisy, nm, pmask = corrupt_uniform(k, ids, lm, 63)
            hidden = decoder.forward(pp, cfg, noisy, return_hidden=True)
            s, n = mdlm_loss_from_hidden(
                hidden, pp["lm_head"]["kernel"], ids, nm, pmask, lm
            )
            return s / n

        l, g = jax.value_and_grad(loss)(p)
        u, o = tx.update(g, o, p)
        return optax.apply_updates(p, u), o, l

    losses = []
    for i in range(40):
        params, opt, l = step(params, opt, jax.random.key(i))
        losses.append(float(l))
    assert losses[-1] < losses[0] * 0.7, losses[::10]


def test_generate_mdlm_fills_canvas():
    V, MASKID = 32, 31

    def fake_logits(ids):
        # always predicts token (position % 7) with high confidence
        B, L = ids.shape
        tgt = jnp.arange(L) % 7
        return 10.0 * jax.nn.one_hot(jnp.broadcast_to(tgt, (B, L)), V)

    prompt = jnp.asarray([[1, 2, 3]], jnp.int32)
    out = generate_mdlm(fake_logits, prompt, gen_len=8, mask_token_id=MASKID, steps=4)
    assert out.shape == (1, 11)
    assert not np.asarray(out == MASKID).any()
    np.testing.assert_array_equal(np.asarray(out[0, :3]), [1, 2, 3])
    np.testing.assert_array_equal(np.asarray(out[0, 3:]), np.arange(3, 11) % 7)
